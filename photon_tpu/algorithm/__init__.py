from photon_tpu.algorithm.fixed_effect import FixedEffectCoordinate  # noqa: F401
from photon_tpu.algorithm.random_effect import RandomEffectCoordinate  # noqa: F401
from photon_tpu.algorithm.coordinate_descent import CoordinateDescent  # noqa: F401
