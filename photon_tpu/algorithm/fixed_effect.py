"""Fixed-effect coordinate: one global GLM over the whole (sharded) batch.

Parity target: reference ``FixedEffectCoordinate`` (photon-api
algorithm/FixedEffectCoordinate.scala:31-152: train via
DistributedOptimizationProblem.runWithSampling + broadcast model; score =
map-side dot with broadcast coefficients) and ``DistributedOptimizationProblem``
(optimization/DistributedOptimizationProblem.scala:140: optional down-sampling,
variance computation).

TPU-first: the batch lives sharded over the mesh's data axis; the whole
optimizer run is one jitted program (w replicated by sharding rule — no
broadcast step exists). Down-sampling is a weight mask (shapes stay static).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.game_data import GameBatch
from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import FixedEffectModel
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.ops.variance import coefficient_variances, normalize_variance_type
from photon_tpu.optim.common import OptimizeResult
from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.algorithm.solve_cache import SolveCache, default_cache
from photon_tpu.obs.trace import span
from photon_tpu.sampling.down_sampler import DownSampler
from photon_tpu.types import TaskType, VarianceComputationType

Array = jax.Array


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    coordinate_id: str
    feature_shard: str
    task: TaskType
    objective: GLMObjective
    optimizer_spec: OptimizerSpec = dataclasses.field(default_factory=OptimizerSpec)
    down_sampler: Optional[DownSampler] = None
    # SIMPLE (diag-inverse) or FULL (Cholesky inverse diagonal); bool accepted
    # for compatibility (True → SIMPLE).
    compute_variance: object = VarianceComputationType.NONE
    dim: Optional[int] = None  # inferred from the batch if None
    # Shared compiled-executable cache (algorithm/solve_cache.py): the full
    # optimizer run is one jitted program per (objective, spec), reused
    # across CD passes and across coordinates with identical configs.
    solve_cache: Optional[SolveCache] = None

    def __post_init__(self):
        self.compute_variance = normalize_variance_type(self.compute_variance)
        if self.solve_cache is None:
            self.solve_cache = default_cache()

    def train(
        self,
        batch: GameBatch,
        residual_scores: Optional[Array] = None,
        initial_model: Optional[FixedEffectModel] = None,
    ) -> Tuple[FixedEffectModel, OptimizeResult]:
        lb = batch.labeled_batch(self.feature_shard, residual_scores)
        if self.down_sampler is not None:
            # Down-sampling as reweighting mask — static shapes
            # (DistributedOptimizationProblem.runWithSampling:140-166 role).
            lb = self.down_sampler.apply(lb)
        d = lb.dim
        w0 = (
            initial_model.model.coefficients.means
            if initial_model is not None
            else jnp.zeros((d,), lb.label.dtype)
        )
        # Models live in MODEL space; solves run in the normalization-folded
        # transformed space (reference Optimizer.scala:167 converts the warm
        # start in, DistributedOptimizationProblem.scala:127 converts the
        # result out).
        norm = self.objective.normalization
        folded = norm is not None and not norm.is_identity
        if folded:
            w0 = norm.model_to_transformed_space(w0)
        solve = self.solve_cache.fe_solver(self.objective, self.optimizer_spec)
        # Host-wall span of the dispatch (the solve itself runs async on
        # device; nothing here blocks).
        with span("fe_solve"):
            result = solve(w0, lb)
        # SIMPLE/FULL variance computation
        # (DistributedOptimizationProblem.scala:83-103 role). Evaluated at
        # the transformed-space optimum (self-consistent with the folded
        # objective — the reference instead feeds model-space coefficients
        # to the folded Hessian) and mapped to model space via factors².
        variances = coefficient_variances(
            self.objective, result.w, lb, self.compute_variance
        )
        w_model = norm.transformed_to_model_space(result.w) if folded else result.w
        if folded and variances is not None and norm.factors is not None:
            variances = variances * norm.factors**2
        model = FixedEffectModel(
            GeneralizedLinearModel(Coefficients(w_model, variances), self.task),
            self.feature_shard,
        )
        return model, result

    def train_from_stream(
        self,
        chunks,
        residual_scores: Optional[Array] = None,
        initial_model: Optional[FixedEffectModel] = None,
    ) -> Tuple[FixedEffectModel, OptimizeResult]:
        """Train from a pipelined chunk stream (io/pipeline.py
        ``BatchChunk`` iterator — e.g. ``stream_device_batches`` or a
        ``ChunkReplayCache`` replay routed through ``device_chunks_from``).

        Chunks concatenate ON DEVICE as they arrive, so each chunk's
        decode/assembly/H2D overlaps earlier chunks' placement via async
        dispatch; the solve then runs exactly as :meth:`train` — same
        compiled executable, same result. Feed unpadded chunks
        (``pad_rows_to=None``): the optimizer is one whole-batch jitted
        program, so row padding would embed inert rows in the objective.
        """
        from photon_tpu.io.pipeline import materialize_game_batch

        return self.train(
            materialize_game_batch(chunks), residual_scores, initial_model
        )

    def score(self, model: FixedEffectModel, batch: GameBatch) -> Array:
        return model.score(batch)

    def zero_model(self) -> FixedEffectModel:
        assert self.dim is not None, "dim required for zero_model"
        return FixedEffectModel(
            GeneralizedLinearModel.zeros(self.dim, self.task), self.feature_shard
        )
