from photon_tpu.utils.timed import Timed  # noqa: F401
from photon_tpu.utils.events import EventEmitter, Event  # noqa: F401
