"""Cooperative SIGTERM/SIGINT handling for the training drivers.

One signal requests a graceful stop: work loops poll
:func:`shutdown_requested` at safe boundaries (a coordinate-descent pass
boundary, a λ-sweep step), persist a final checkpoint, and raise
:class:`GracefulShutdown`; the driver catches it, finalizes the run report,
and exits ``128 + signum`` — the conventional killed-by-signal code, so
orchestrators classify the exit correctly. A SECOND signal keeps its default
(fatal) behavior: the handler restores the previous handlers on first
receipt, so an operator can always escalate past a stuck step.
"""

from __future__ import annotations

import contextlib
import logging
import os as _os
import signal as _signal
import threading
import time as _time
from typing import Dict, Iterable, Optional


class GracefulShutdown(Exception):
    """A SIGTERM/SIGINT was received and the cooperative shutdown point was
    reached: the work loop stopped at a safe boundary (checkpoint written).
    Drivers catch this, finalize telemetry, and exit 128+signum."""

    def __init__(self, signum: int):
        super().__init__(f"terminated by signal {signum}")
        self.signum = signum


_TERM_STATE = {"signum": None}


def shutdown_requested() -> Optional[int]:
    """Signum of a received SIGTERM/SIGINT inside :func:`handle_termination`,
    else None."""
    return _TERM_STATE["signum"]


def terminate_children(
    pids: Iterable[int],
    timeout_s: float = 10.0,
    poll_s: float = 0.05,
) -> Dict[int, int]:
    """Graceful multi-process drain: SIGTERM every child, wait up to
    ``timeout_s`` for ALL to exit (polling ``waitpid(WNOHANG)``), then
    SIGKILL stragglers. Returns {pid: exit code} (negative = killed by
    signal, per ``waitstatus_to_exitcode``). Safe against children that
    already died — ESRCH/ECHILD are treated as 'gone'."""
    log = logging.getLogger("photon_tpu")
    pending = {}
    exits: Dict[int, int] = {}
    for pid in pids:
        try:
            _os.kill(pid, _signal.SIGTERM)
            pending[pid] = True
        except ProcessLookupError:
            pending[pid] = True  # already dead; still needs reaping
    deadline = _time.monotonic() + timeout_s
    while pending:
        for pid in list(pending):
            try:
                done, status = _os.waitpid(pid, _os.WNOHANG)
            except ChildProcessError:
                exits[pid] = 0  # reaped elsewhere (or not our child)
                del pending[pid]
                continue
            if done == pid:
                exits[pid] = _os.waitstatus_to_exitcode(status)
                del pending[pid]
        if not pending:
            break
        if _time.monotonic() >= deadline:
            for pid in list(pending):
                log.warning(
                    "child pid %d ignored SIGTERM for %.1fs; escalating "
                    "to SIGKILL", pid, timeout_s,
                )
                try:
                    _os.kill(pid, _signal.SIGKILL)
                except ProcessLookupError:
                    pass
                try:
                    _, status = _os.waitpid(pid, 0)
                    exits[pid] = _os.waitstatus_to_exitcode(status)
                except ChildProcessError:
                    exits[pid] = 0
                del pending[pid]
            break
        _time.sleep(poll_s)
    return exits


@contextlib.contextmanager
def handle_termination():
    """Convert the FIRST SIGTERM/SIGINT into a cooperative shutdown request
    (see :func:`shutdown_requested`); previous handlers are restored
    immediately, so a second signal is fatal. No-op off the main thread
    (signal handlers are main-thread-only in CPython)."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    _TERM_STATE["signum"] = None
    prev = {}

    def _restore():
        for sig, h in prev.items():
            try:
                _signal.signal(sig, h)
            except (ValueError, OSError):
                pass

    def _on_signal(signum, frame):
        _TERM_STATE["signum"] = signum
        logging.getLogger("photon_tpu").warning(
            "received signal %d: finishing the current step, writing a "
            "final checkpoint, then exiting (send again to kill now)",
            signum,
        )
        _restore()  # second signal falls through to the default handler

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        prev[sig] = _signal.signal(sig, _on_signal)
    try:
        yield
    finally:
        _restore()
        _TERM_STATE["signum"] = None
