"""Cooperative SIGTERM/SIGINT handling for the training drivers.

One signal requests a graceful stop: work loops poll
:func:`shutdown_requested` at safe boundaries (a coordinate-descent pass
boundary, a λ-sweep step), persist a final checkpoint, and raise
:class:`GracefulShutdown`; the driver catches it, finalizes the run report,
and exits ``128 + signum`` — the conventional killed-by-signal code, so
orchestrators classify the exit correctly. A SECOND signal keeps its default
(fatal) behavior: the handler restores the previous handlers on first
receipt, so an operator can always escalate past a stuck step.
"""

from __future__ import annotations

import contextlib
import logging
import signal as _signal
import threading
from typing import Optional


class GracefulShutdown(Exception):
    """A SIGTERM/SIGINT was received and the cooperative shutdown point was
    reached: the work loop stopped at a safe boundary (checkpoint written).
    Drivers catch this, finalize telemetry, and exit 128+signum."""

    def __init__(self, signum: int):
        super().__init__(f"terminated by signal {signum}")
        self.signum = signum


_TERM_STATE = {"signum": None}


def shutdown_requested() -> Optional[int]:
    """Signum of a received SIGTERM/SIGINT inside :func:`handle_termination`,
    else None."""
    return _TERM_STATE["signum"]


@contextlib.contextmanager
def handle_termination():
    """Convert the FIRST SIGTERM/SIGINT into a cooperative shutdown request
    (see :func:`shutdown_requested`); previous handlers are restored
    immediately, so a second signal is fatal. No-op off the main thread
    (signal handlers are main-thread-only in CPython)."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    _TERM_STATE["signum"] = None
    prev = {}

    def _restore():
        for sig, h in prev.items():
            try:
                _signal.signal(sig, h)
            except (ValueError, OSError):
                pass

    def _on_signal(signum, frame):
        _TERM_STATE["signum"] = signum
        logging.getLogger("photon_tpu").warning(
            "received signal %d: finishing the current step, writing a "
            "final checkpoint, then exiting (send again to kill now)",
            signum,
        )
        _restore()  # second signal falls through to the default handler

    for sig in (_signal.SIGTERM, _signal.SIGINT):
        prev[sig] = _signal.signal(sig, _on_signal)
    try:
        yield
    finally:
        _restore()
        _TERM_STATE["signum"] = None
