"""Deterministic, plan-driven fault injection.

Photon ML inherited fault tolerance from Spark (lineage recompute, task
retry — PAPER.md §2.9); the single-process rebuild has to *earn* it, and a
robustness layer that is never exercised is indistinguishable from one that
does not work. This module is the exercise machinery: a seeded, plan-driven
injector with named hook points in the ingest pipeline
(``ingest.source``/``ingest.assemble``/``ingest.h2d``), the solve engine
(``solve.fe``/``solve.re_block``), the checkpoint writer
(``checkpoint.save``/``checkpoint.after_save``), and the serving store/engine
(``serve.store_resolve``/``serve.store_upload``/``serve.score``/
``serve.reload``), and the streaming freshness loop
(``serve.feedback`` — the spool's label-join/segment writer, where ``torn``
tears the active segment mid-record and ``enospc`` drops the join — and
``stream.consume`` — the updater's per-segment read and pre-train step,
where ``kill`` crashes the updater mid-cycle). The scorer fleet adds
``serve.replica_kill``: fired from each replica's main-thread heartbeat
(labelled with the replica id, targeted per replica by setting
``PHOTON_TPU_FAULT_PLAN`` in that replica's environment), where ``kill``
SIGKILLs the whole replica mid-traffic — the failover drill that proves a
dead member's shard degrades to FE-only scoring instead of erroring.

A **plan** is JSON — inline or a file path — selected by the
``PHOTON_TPU_FAULT_PLAN`` environment variable (or programmatically via
:func:`configure` in tests):

    {"seed": 7, "rules": [
        {"site": "ingest.source", "kind": "transient", "p": 0.2},
        {"site": "solve.re_block", "kind": "nan", "at": [1]},
        {"site": "checkpoint.after_save", "kind": "kill", "at": [0]}
    ]}

Rules fire either probabilistically (``p``, via a per-rule
``np.random.default_rng`` seeded from plan seed + site, so runs are
reproducible and independent of call order elsewhere) or at explicit per-site
call indices (``at``), optionally bounded by ``max_count``. Kinds:

- ``transient``  — raise :class:`TransientInjectedFault` (an ``OSError``
  subclass, so IO retry classification treats it as retryable).
- ``permanent``  — raise :class:`PermanentInjectedFault`.
- ``nan``        — the hook poisons an array (first row → NaN), simulating
  decode corruption / non-finite gradients.
- ``torn``       — checkpoint writer leaves a truncated file at the final
  step path (simulating a machine crash after rename, before data blocks
  hit disk) and raises.
- ``kill``       — ``SIGKILL`` the current process at the hook (used by the
  ``ci.sh faults`` kill-and-resume smoke).
- ``enospc``     — raise :class:`EnospcInjectedFault` (an ``OSError`` with
  ``errno == ENOSPC``), simulating a full disk at a writer site
  (``checkpoint.io``/``telemetry.write``/``spool.write``/
  ``deadletter.write``/``re_store.spill``).
- ``oom``        — raise :class:`DeviceOomInjectedFault` (a ``RuntimeError``
  whose message contains ``RESOURCE_EXHAUSTED``), simulating a device
  allocator failure at an upload site (``re_store.upload``/
  ``serve.store_upload``/``serve.warm_up``).
- ``rss``        — only acts at the ``rss.sample`` site, where the host
  memory watchdog (:mod:`photon_tpu.utils.resources`) interprets it as a
  simulated pressure reading (``message`` containing ``"hard"`` → hard
  pressure, else soft). A bare :func:`check` ignores it, like ``nan``.

Every injection increments ``faults_injected_total{site,kind}`` in the
metrics registry, so fault counts land in the run report. With no plan
configured the hooks are near-free (one attribute read + truthiness check).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

FAULT_PLAN_ENV = "PHOTON_TPU_FAULT_PLAN"

KINDS = ("transient", "permanent", "nan", "torn", "kill", "enospc", "oom",
         "rss")


class InjectedFault(Exception):
    """Base class for all injected failures (never raised directly)."""


class TransientInjectedFault(InjectedFault, OSError):
    """Retryable injected failure — subclasses OSError so the pipeline's
    transient-error classification catches it without special cases."""


class PermanentInjectedFault(InjectedFault, RuntimeError):
    """Non-retryable injected failure."""


class EnospcInjectedFault(InjectedFault, OSError):
    """Injected disk-full failure — an ``OSError`` carrying
    ``errno == ENOSPC`` so every writer's real ENOSPC policy (and
    :func:`photon_tpu.utils.resources.is_enospc`) handles it unchanged."""

    def __init__(self, message: str):
        import errno as _errno

        OSError.__init__(self, _errno.ENOSPC, message)


class DeviceOomInjectedFault(InjectedFault, RuntimeError):
    """Injected device allocator failure. The message embeds
    ``RESOURCE_EXHAUSTED`` so code that classifies real ``XlaRuntimeError``
    OOMs by substring (:func:`photon_tpu.utils.resources.is_device_oom`)
    takes the same containment path for injected ones."""

    def __init__(self, message: str):
        RuntimeError.__init__(self, f"RESOURCE_EXHAUSTED: {message}")


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One injection rule. ``site`` may be exact or an ``fnmatch`` glob."""

    site: str
    kind: str = "transient"
    p: float = 0.0
    at: Tuple[int, ...] = ()
    max_count: Optional[int] = None
    message: str = "injected fault"

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {KINDS}")
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"fault probability must be in [0,1], got {self.p}")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    seed: int = 0
    rules: Tuple[FaultRule, ...] = ()

    @staticmethod
    def from_obj(obj: Dict[str, Any]) -> "FaultPlan":
        rules = tuple(
            FaultRule(
                site=r["site"],
                kind=r.get("kind", "transient"),
                p=float(r.get("p", 0.0)),
                at=tuple(int(i) for i in r.get("at", ())),
                max_count=r.get("max_count"),
                message=r.get("message", "injected fault"),
            )
            for r in obj.get("rules", ())
        )
        return FaultPlan(seed=int(obj.get("seed", 0)), rules=rules)

    @staticmethod
    def from_env() -> Optional["FaultPlan"]:
        raw = os.environ.get(FAULT_PLAN_ENV, "").strip()
        if not raw:
            return None
        if not raw.startswith("{"):  # a file path, not inline JSON
            with open(raw) as f:
                raw = f.read()
        return FaultPlan.from_obj(json.loads(raw))


def _site_rng(seed: int, site: str) -> np.random.Generator:
    # Stable across processes (hash() is salted; crc32 is not).
    return np.random.default_rng((seed << 32) ^ zlib.crc32(site.encode()))


class FaultInjector:
    """Evaluates a :class:`FaultPlan`. Thread-safe; per-(rule, site) call
    counters and RNG streams make firing sequences deterministic for a given
    plan regardless of what other sites do."""

    def __init__(self, plan: Optional[FaultPlan]):
        self._plan = plan
        self._lock = threading.Lock()
        self._calls: Dict[Tuple[int, str], int] = {}
        self._fired: Dict[Tuple[int, str], int] = {}
        self._rngs: Dict[Tuple[int, str], np.random.Generator] = {}
        self.enabled = bool(plan and plan.rules)

    def _matches(self, rule: FaultRule, site: str) -> bool:
        if rule.site == site:
            return True
        if any(c in rule.site for c in "*?["):
            import fnmatch

            return fnmatch.fnmatch(site, rule.site)
        return False

    def fire(self, site: str, label: Optional[str] = None) -> Optional[FaultRule]:
        """Advance per-rule call counters for ``site``; return the first rule
        that fires here (and count it), else None."""
        if not self.enabled:
            return None
        hit: Optional[FaultRule] = None
        with self._lock:
            assert self._plan is not None
            for idx, rule in enumerate(self._plan.rules):
                if not self._matches(rule, site):
                    continue
                key = (idx, site)
                n = self._calls.get(key, 0)
                self._calls[key] = n + 1
                if hit is not None:
                    continue  # still advance counters for later rules
                fired = self._fired.get(key, 0)
                if rule.max_count is not None and fired >= rule.max_count:
                    continue
                trigger = n in rule.at
                if not trigger and rule.p > 0.0:
                    rng = self._rngs.get(key)
                    if rng is None:
                        rng = self._rngs[key] = _site_rng(self._plan.seed, site)
                    trigger = bool(rng.random() < rule.p)
                if trigger:
                    self._fired[key] = fired + 1
                    hit = rule
        if hit is not None:
            self._record(site, hit, label)
        return hit

    def _record(self, site: str, rule: FaultRule, label: Optional[str]) -> None:
        try:
            from photon_tpu.obs import registry

            registry().counter(
                "faults_injected_total", site=site, kind=rule.kind
            ).inc()
        except Exception:  # metrics must never mask the fault path itself
            pass
        logger.warning(
            "fault injected at %s%s: kind=%s", site,
            f" ({label})" if label else "", rule.kind,
        )

    def counts(self) -> Dict[str, int]:
        """Total injections per site (for tests and reports)."""
        with self._lock:
            out: Dict[str, int] = {}
            for (_, site), n in self._fired.items():
                out[site] = out.get(site, 0) + n
            return out


def exception_for(rule: FaultRule, site: str) -> InjectedFault:
    if rule.kind == "permanent":
        return PermanentInjectedFault(f"{rule.message} [{site}]")
    if rule.kind == "enospc":
        return EnospcInjectedFault(f"{rule.message} [{site}]")
    if rule.kind == "oom":
        return DeviceOomInjectedFault(f"{rule.message} [{site}]")
    return TransientInjectedFault(f"{rule.message} [{site}]")


# ---------------------------------------------------------------------------
# Process-wide injector + hook helpers (the only API hook sites use)
# ---------------------------------------------------------------------------

_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def injector() -> FaultInjector:
    global _injector
    inj = _injector
    if inj is None:
        with _injector_lock:
            inj = _injector
            if inj is None:
                inj = _injector = FaultInjector(FaultPlan.from_env())
    return inj


def configure(plan: Optional[FaultPlan], seed: Optional[int] = None) -> FaultInjector:
    """Install an explicit plan (tests / drivers). ``configure(None)``
    disables injection until :func:`reset`."""
    global _injector
    if plan is not None and seed is not None:
        plan = dataclasses.replace(plan, seed=seed)
    with _injector_lock:
        _injector = FaultInjector(plan)
        return _injector


def reset() -> None:
    """Drop any configured injector; the next hook re-reads the environment."""
    global _injector
    with _injector_lock:
        _injector = None


def active(site: Optional[str] = None) -> bool:
    """Cheap guard for hook sites that need setup work before injecting."""
    inj = injector()
    if not inj.enabled:
        return False
    if site is None:
        return True
    assert inj._plan is not None
    return any(inj._matches(r, site) for r in inj._plan.rules)


def check(site: str, label: Optional[str] = None) -> None:
    """Raise the planned fault for ``site`` if one fires on this call.
    ``kill`` rules SIGKILL the process (crash simulation, no cleanup)."""
    inj = injector()
    if not inj.enabled:
        return
    rule = inj.fire(site, label)
    if rule is None:
        return
    if rule.kind == "kill":
        logger.warning("fault plan: SIGKILL self at %s", site)
        os.kill(os.getpid(), signal.SIGKILL)
    if rule.kind in ("nan", "rss"):
        # nan rules only act through poison(), rss rules only through the
        # RSS watchdog's sampler; a bare check ignores both.
        return
    raise exception_for(rule, site)


def poison(site: str, array, label: Optional[str] = None):
    """If a ``nan`` rule fires at ``site``, return ``array`` with its first
    row (or element) set to NaN; otherwise return it unchanged. Works on
    numpy and jax arrays; the jax path is an in-trace-safe device op."""
    inj = injector()
    if not inj.enabled:
        return array
    rule = inj.fire(site, label)
    if rule is None:
        return array
    if rule.kind == "kill":
        logger.warning("fault plan: SIGKILL self at %s", site)
        os.kill(os.getpid(), signal.SIGKILL)
    if rule.kind == "rss":
        return array  # rss rules only act through the watchdog sampler
    if rule.kind != "nan":
        raise exception_for(rule, site)
    if isinstance(array, np.ndarray):
        out = np.array(array, copy=True)
        out[(0,) * max(out.ndim - 1, 1)] = np.nan
        return out
    import jax.numpy as jnp

    if array.ndim == 0:
        return jnp.asarray(jnp.nan, dtype=array.dtype)
    return array.at[0].set(jnp.nan)


__all__ = [
    "FAULT_PLAN_ENV",
    "DeviceOomInjectedFault",
    "EnospcInjectedFault",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "PermanentInjectedFault",
    "TransientInjectedFault",
    "active",
    "check",
    "configure",
    "exception_for",
    "injector",
    "poison",
    "reset",
]
