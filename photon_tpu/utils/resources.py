"""Resource-exhaustion containment: one policy for device OOM, disk-full,
and host memory pressure.

Every budget in the tree (device residency bytes, replay-cache bytes,
pipeline queue depths, telemetry report bytes) is a *guess* about a ceiling
the OS and the XLA allocator enforce for real. This module is what happens
when the guess is wrong, governed by a single degradation priority:

    model artifacts (checkpoints, published generations)
        > training progress
        > observability (telemetry, dead letters, reports)

Concretely:

- **Device OOM** (``XlaRuntimeError: RESOURCE_EXHAUSTED``, caught nowhere
  before this layer): the residency stores evict harder, shrink their
  effective byte budget toward the floor (the largest single block — the
  same floor :class:`~photon_tpu.data.residency.ByteBudgetLru` already
  admits at), and retry once. Bit parity is preserved because the
  out-of-core path is value-identical at any budget. A hard
  :class:`DeviceMemoryError` fires only when the floor itself cannot fit.
- **Disk full** (``ENOSPC``/``EDQUOT``): observability writers degrade to
  counted drops (``disk_enospc_total{site}``, never raising into the
  training loop); the replay spool falls back to the legacy re-stream path
  and removes its partial file; the checkpoint writer prunes older steps
  (keep-last-K) and retries before giving up, never leaving a tmp file.
- **Host RSS pressure**: a cgroup-aware sampling thread
  (:class:`RssWatchdog`) publishes a pressure level that allocating layers
  poll — pipeline queue depths and the serving admission cap tighten at
  *soft* pressure; at *hard* pressure the training loop's pass-boundary
  check raises a clean, actionable :class:`HostMemoryPressureError` instead
  of letting the kernel OOM-killer produce an unexplained SIGKILL.

All paths are exercised by the ``enospc``/``oom``/``rss`` kinds in
:mod:`photon_tpu.utils.faults` and the ``bench.py --exhaustion-soak`` /
``ci.sh exhaustion`` smokes.
"""

from __future__ import annotations

import errno
import gc
import logging
import os
import threading
from typing import Callable, Optional

from photon_tpu.utils import faults

logger = logging.getLogger(__name__)

RSS_LIMIT_ENV = "PHOTON_TPU_RSS_LIMIT_BYTES"
RSS_SOFT_ENV = "PHOTON_TPU_RSS_SOFT_FRACTION"
RSS_HARD_ENV = "PHOTON_TPU_RSS_HARD_FRACTION"

#: Pressure levels published by the watchdog (monotone: OK < SOFT < HARD).
LEVEL_OK, LEVEL_SOFT, LEVEL_HARD = 0, 1, 2
_LEVEL_NAMES = {LEVEL_OK: "ok", LEVEL_SOFT: "soft", LEVEL_HARD: "hard"}


class ResourceExhaustedError(RuntimeError):
    """Base for clean, actionable exhaustion failures raised by this layer
    (as opposed to a raw allocator traceback or an OOM-killer SIGKILL)."""


class DeviceMemoryError(ResourceExhaustedError):
    """Device memory exhausted even after evict-harder + budget shrink down
    to the floor (largest single block). The message says which knob to
    turn; there is no safe automatic recovery below the floor."""


class HostMemoryPressureError(ResourceExhaustedError):
    """Host RSS crossed the hard-pressure threshold. Raised at a cooperative
    check point (pass boundary), before the kernel OOM-killer would have
    SIGKILLed the process with no explanation."""


# ---------------------------------------------------------------------------
# Failure classification
# ---------------------------------------------------------------------------


def is_device_oom(exc: BaseException) -> bool:
    """True for a device allocator OOM: a real ``XlaRuntimeError`` whose
    message carries ``RESOURCE_EXHAUSTED`` / ``Out of memory``, or the
    injected :class:`~photon_tpu.utils.faults.DeviceOomInjectedFault`
    (whose message embeds the same marker). Classified by message rather
    than type so we need no import of jaxlib internals."""
    if not isinstance(exc, Exception):
        return False
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg


def is_enospc(exc: BaseException) -> bool:
    """True for a disk-full/quota failure (``ENOSPC`` or ``EDQUOT``),
    including the injected ``enospc`` fault kind which carries the errno."""
    return isinstance(exc, OSError) and exc.errno in (
        errno.ENOSPC,
        getattr(errno, "EDQUOT", errno.ENOSPC),
    )


def _metrics():
    from photon_tpu.obs import registry

    return registry()


# ---------------------------------------------------------------------------
# Device OOM containment
# ---------------------------------------------------------------------------


def oom_retry(
    attempt: Callable[[], object],
    *,
    site: str,
    evict: Optional[Callable[[int], None]] = None,
    retries: int = 1,
    counter: str = "device_oom_retries_total",
    **labels,
):
    """Run ``attempt``; on device OOM call ``evict(attempt_index)`` (the
    caller's evict-harder / budget-shrink hook), ``gc.collect()`` to release
    dropped device buffers, and retry up to ``retries`` times. Counts each
    contained OOM in ``counter{site=...}``. Non-OOM exceptions propagate
    untouched; the final OOM propagates to the caller, which decides whether
    it is a hard :class:`DeviceMemoryError`."""
    for i in range(retries + 1):
        try:
            return attempt()
        except Exception as exc:  # noqa: BLE001 — classified below
            if not is_device_oom(exc) or i >= retries:
                raise
            logger.warning(
                "device OOM at %s (attempt %d/%d): evicting harder and "
                "retrying: %s", site, i + 1, retries + 1, exc,
            )
            try:
                _metrics().counter(counter, site=site, **labels).inc()
            except Exception:
                pass
            if evict is not None:
                evict(i)
            gc.collect()
    raise AssertionError("unreachable")  # pragma: no cover


# ---------------------------------------------------------------------------
# Disk-full containment
# ---------------------------------------------------------------------------


class DiskBudgetGuard:
    """Shared ENOSPC policy for one writer site (replay spool,
    ``--re-spill-dir``, dead-letter sidecar, telemetry sink, checkpoint
    writer). It does three things, all cheap:

    - ``check()`` runs the fault hook for the site, so an ``enospc`` rule in
      the plan raises exactly where a real full disk would;
    - ``record(exc)`` classifies an ``OSError`` (counts
      ``disk_enospc_total{site}`` vs ``disk_write_failures_total{site}``)
      and returns True when it was a disk-space failure;
    - ``cleanup(*paths)`` best-effort-unlinks partial artifacts so a failed
      write never leaks the very space a retry needs.

    The *policy* on failure (drop / fall back / prune-and-retry) stays with
    the caller, because it differs by degradation priority.
    """

    def __init__(self, site: str):
        self.site = site

    def check(self) -> None:
        faults.check(self.site)

    def record(self, exc: BaseException) -> bool:
        full = is_enospc(exc)
        try:
            name = "disk_enospc_total" if full else "disk_write_failures_total"
            _metrics().counter(name, site=self.site).inc()
        except Exception:
            pass
        return full

    def cleanup(self, *paths: Optional[str]) -> None:
        for p in paths:
            if not p:
                continue
            try:
                os.unlink(p)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Host RSS watchdog
# ---------------------------------------------------------------------------


def _cgroup_mem_limit() -> Optional[int]:
    """Container memory limit, cgroup v2 then v1 (same spirit as
    ``io.columnar._available_cores``). None when unlimited/undetectable."""
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            raw = open(path).read().strip()
        except OSError:
            continue
        if raw == "max":
            continue
        try:
            limit = int(raw)
        except ValueError:
            continue
        # v1 reports ~PTRDIFF_MAX when unlimited.
        if 0 < limit < (1 << 60):
            return limit
    return None


def _read_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource as _resource

        return _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


class RssWatchdog:
    """Samples host RSS against a limit (env override → cgroup) on a daemon
    thread and publishes a pressure level other layers poll.

    - ``level()`` → LEVEL_OK / LEVEL_SOFT / LEVEL_HARD (lock-free read).
    - ``check(site)`` → raises :class:`HostMemoryPressureError` at hard
      pressure; called at cooperative boundaries (CD pass loop, λ sweep).
    - Gauges ``host_rss_bytes`` / ``host_rss_limit_bytes`` /
      ``host_rss_pressure_level``; transitions count
      ``rss_pressure_events_total{level}``.
    - The ``rss.sample`` fault site lets a plan simulate pressure: a fired
      ``rss`` rule with ``"hard"`` in its message reads as hard pressure,
      any other fired ``rss`` rule as soft.

    With no detectable limit the watchdog is inert (level stays OK) — same
    contract as an uncontainerised host with abundant RAM.
    """

    def __init__(
        self,
        limit_bytes: Optional[int] = None,
        soft_fraction: Optional[float] = None,
        hard_fraction: Optional[float] = None,
        interval_s: float = 0.5,
    ):
        if limit_bytes is None:
            env = os.environ.get(RSS_LIMIT_ENV, "").strip()
            if env:
                limit_bytes = int(env)
            else:
                limit_bytes = _cgroup_mem_limit()
        self.limit_bytes = limit_bytes
        self.soft_fraction = float(
            soft_fraction if soft_fraction is not None
            else os.environ.get(RSS_SOFT_ENV, 0.85))
        self.hard_fraction = float(
            hard_fraction if hard_fraction is not None
            else os.environ.get(RSS_HARD_ENV, 0.95))
        self.interval_s = interval_s
        self._level = LEVEL_OK
        self._last_rss = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ----------------------------------------------------------

    def sample(self) -> int:
        """Take one sample and return the new level. Called by the thread
        loop; tests and single-threaded drivers may call it directly."""
        rss = _read_rss_bytes() or 0
        self._last_rss = rss
        level = LEVEL_OK
        if self.limit_bytes:
            frac = rss / self.limit_bytes
            if frac >= self.hard_fraction:
                level = LEVEL_HARD
            elif frac >= self.soft_fraction:
                level = LEVEL_SOFT
        rule = faults.injector().fire("rss.sample")
        if rule is not None and rule.kind == "rss":
            level = LEVEL_HARD if "hard" in rule.message else LEVEL_SOFT
        prev, self._level = self._level, level
        try:
            m = _metrics()
            m.gauge("host_rss_bytes").set(rss)
            m.gauge("host_rss_limit_bytes").set(self.limit_bytes or 0)
            m.gauge("host_rss_pressure_level").set(level)
            if level != prev and level != LEVEL_OK:
                m.counter("rss_pressure_events_total",
                          level=_LEVEL_NAMES[level]).inc()
        except Exception:
            pass
        if level != prev and level != LEVEL_OK:
            logger.warning(
                "host memory pressure %s: rss=%d limit=%s (queue depths and "
                "admission caps tighten; hard pressure fails the run cleanly "
                "at the next pass boundary)",
                _LEVEL_NAMES[level], rss, self.limit_bytes,
            )
        return level

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # the watchdog must never kill its host
                logger.exception("rss watchdog sample failed")

    def start(self) -> "RssWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="rss-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- polling API -------------------------------------------------------

    def level(self) -> int:
        return self._level

    def check(self, site: str = "") -> None:
        if self._level >= LEVEL_HARD:
            raise HostMemoryPressureError(
                f"host RSS {self._last_rss} of limit {self.limit_bytes} "
                f"crossed the hard-pressure fraction "
                f"{self.hard_fraction:.2f}"
                + (f" at {site}" if site else "")
                + "; stopping cleanly before the kernel OOM-killer does it "
                "for us. Lower --replay-cache-mb / --re-device-budget-mb / "
                "queue depths, raise the container memory limit, or tune "
                f"{RSS_SOFT_ENV}/{RSS_HARD_ENV}."
            )


# ---------------------------------------------------------------------------
# Process-wide watchdog + pressure helpers (the only API poll sites use)
# ---------------------------------------------------------------------------

_watchdog: Optional[RssWatchdog] = None
_watchdog_lock = threading.Lock()


def watchdog() -> Optional[RssWatchdog]:
    return _watchdog


def start_watchdog(**kwargs) -> RssWatchdog:
    """Install and start the process-wide watchdog (CLI entry points call
    this once). Idempotent: a second call returns the existing one."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is None:
            _watchdog = RssWatchdog(**kwargs).start()
        return _watchdog


def stop_watchdog() -> None:
    global _watchdog
    with _watchdog_lock:
        wd, _watchdog = _watchdog, None
    if wd is not None:
        wd.stop()


def pressure_level() -> int:
    wd = _watchdog
    return wd.level() if wd is not None else LEVEL_OK


def memory_pressure() -> bool:
    """True at soft pressure or worse — layers that can cheaply hold less
    (replay cache admission, prefetch depth) consult this."""
    return pressure_level() >= LEVEL_SOFT


def tightened_depth(depth: int) -> int:
    """Pipeline prefetch/queue depth under the current pressure level:
    unchanged when OK, 1 under any pressure (each queue slot pins a decoded
    host block, so depth is the cheapest RSS to give back)."""
    return 1 if (pressure_level() >= LEVEL_SOFT and depth > 1) else depth


def tightened_cap(cap: int) -> int:
    """Admission-queue cap under the current pressure level: unchanged when
    OK, halved at soft pressure, quartered (min 1) at hard — serving sheds
    by backpressure rather than dying by OOM-killer."""
    level = pressure_level()
    if level >= LEVEL_HARD:
        return max(1, cap // 4)
    if level >= LEVEL_SOFT:
        return max(1, cap // 2)
    return cap


def check_memory(site: str = "") -> None:
    """Raise :class:`HostMemoryPressureError` at hard pressure. Training
    loops call this at pass boundaries, next to the shutdown poll."""
    wd = _watchdog
    if wd is not None:
        wd.check(site)


__all__ = [
    "LEVEL_HARD",
    "LEVEL_OK",
    "LEVEL_SOFT",
    "DeviceMemoryError",
    "DiskBudgetGuard",
    "HostMemoryPressureError",
    "ResourceExhaustedError",
    "RssWatchdog",
    "check_memory",
    "is_device_oom",
    "is_enospc",
    "memory_pressure",
    "oom_retry",
    "pressure_level",
    "start_watchdog",
    "stop_watchdog",
    "tightened_cap",
    "tightened_depth",
    "watchdog",
]
