"""Driver I/O utilities: date-range input resolution, output-dir lifecycle,
text read/write, and the driver logger.

Parity targets (reference photon-client):
- ``DateRange`` / ``DaysRange`` (util/DateRange.scala, util/DaysRange.scala):
  "yyyyMMdd-yyyyMMdd" date ranges and "start-end" days-ago ranges used to
  select daily input directories.
- ``IOUtils`` (util/IOUtils.scala): resolve input paths within a date range
  (daily-partitioned ``<base>/daily/yyyy/MM/dd`` layout), output-dir
  lifecycle (fail or delete when present), text file read/write.
- ``PhotonLogger`` (util/PhotonLogger.scala:34-68): a driver logger that also
  writes the run log into the job's output directory.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
import os
import shutil
from typing import List, Optional, Sequence

_DATE_PATTERN = "%Y%m%d"
_DELIM = "-"


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] date range (reference DateRange.scala)."""

    start: _dt.date
    end: _dt.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end date {self.end}"
            )

    @staticmethod
    def parse(spec: str) -> "DateRange":
        """Parse "yyyyMMdd-yyyyMMdd"."""
        try:
            start_s, end_s = spec.split(_DELIM)
            start = _dt.datetime.strptime(start_s, _DATE_PATTERN).date()
            end = _dt.datetime.strptime(end_s, _DATE_PATTERN).date()
        except ValueError as e:
            raise ValueError(f"Couldn't parse the date range: {spec}") from e
        return DateRange(start, end)

    def dates(self) -> List[_dt.date]:
        n = (self.end - self.start).days + 1
        return [self.start + _dt.timedelta(days=i) for i in range(n)]

    def __str__(self) -> str:
        return (
            f"{self.start.strftime(_DATE_PATTERN)}{_DELIM}"
            f"{self.end.strftime(_DATE_PATTERN)}"
        )


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """"start-end" days-ago range, resolved against today
    (reference DaysRange.scala). start must be further back than end."""

    start_days_ago: int
    end_days_ago: int

    def __post_init__(self):
        if self.start_days_ago < self.end_days_ago:
            raise ValueError(
                f"Invalid range: start {self.start_days_ago} days ago is more "
                f"recent than end {self.end_days_ago} days ago"
            )
        if self.end_days_ago < 0:
            raise ValueError("days-ago values must be non-negative")

    @staticmethod
    def parse(spec: str) -> "DaysRange":
        try:
            start_s, end_s = spec.split(_DELIM)
            start, end = int(start_s), int(end_s)
        except ValueError as e:
            raise ValueError(f"Couldn't parse the days range: {spec}") from e
        return DaysRange(start, end)

    def to_date_range(self, today: Optional[_dt.date] = None) -> DateRange:
        today = today or _dt.date.today()
        return DateRange(
            today - _dt.timedelta(days=self.start_days_ago),
            today - _dt.timedelta(days=self.end_days_ago),
        )


def resolve_range_paths(
    base_dirs: Sequence[str],
    date_range: Optional[DateRange],
    errors_on_missing: bool = True,
) -> List[str]:
    """Expand base input dirs to daily subdirs within the date range.

    Layout: ``<base>/daily/yyyy/MM/dd`` (reference IOUtils.getInputPathsWithinDateRange).
    Without a range, returns the base dirs unchanged.
    """
    if date_range is None:
        return list(base_dirs)
    out: List[str] = []
    missing: List[str] = []
    for base in base_dirs:
        daily = os.path.join(base, "daily")
        root = daily if os.path.isdir(daily) else base
        for d in date_range.dates():
            p = os.path.join(root, f"{d.year:04d}", f"{d.month:02d}", f"{d.day:02d}")
            if os.path.isdir(p):
                out.append(p)
            else:
                missing.append(p)
    if not out and errors_on_missing:
        raise FileNotFoundError(
            f"No input found in {list(base_dirs)} for date range {date_range}"
        )
    if missing:
        # Days absent inside the range are skipped (reference
        # IOUtils.getInputPathsWithinDateRange keeps only existing paths) but
        # loudly: a silent gap means silently training on partial data.
        logging.getLogger(__name__).warning(
            "Date range %s: %d day dir(s) missing and skipped: %s",
            date_range, len(missing), ", ".join(missing[:5]) + ("..." if len(missing) > 5 else ""),
        )
    return out


def process_output_dir(output_dir: str, override: bool) -> None:
    """Output-dir lifecycle (reference IOUtils.processOutputDir,
    Driver.scala:154): fail if it exists non-empty unless override, in which
    case it is deleted first."""
    if os.path.exists(output_dir) and os.listdir(output_dir):
        if not override:
            raise FileExistsError(
                f"Output directory {output_dir} already exists (pass override to replace)"
            )
        shutil.rmtree(output_dir)
    os.makedirs(output_dir, exist_ok=True)


def date_range_from_specs(
    date_range_spec: Optional[str], days_range_spec: Optional[str],
) -> Optional[DateRange]:
    """Resolve the --input-data-date-range / --input-data-days-range pair
    (date range wins, matching GameDriver's precedence)."""
    if date_range_spec:
        return DateRange.parse(date_range_spec)
    if days_range_spec:
        return DaysRange.parse(days_range_spec).to_date_range()
    return None


def write_text(path: str, lines: Sequence[str]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for line in lines:
            f.write(line)
            f.write("\n")


def read_text(path: str) -> List[str]:
    with open(path) as f:
        return [line.rstrip("\n") for line in f]


class PhotonLogger:
    """Driver logger that tees to a log file inside the job output dir
    (reference PhotonLogger.scala:34-68, which writes the driver log to HDFS).
    """

    def __init__(self, output_dir: str, name: str = "photon_tpu",
                 level: int = logging.INFO):
        os.makedirs(output_dir, exist_ok=True)
        self.path = os.path.join(output_dir, f"{name}.log")
        self._logger = logging.getLogger(f"{name}.{id(self)}")
        self._logger.setLevel(level)
        self._logger.propagate = False
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s")
        self._file_handler = logging.FileHandler(self.path)
        self._file_handler.setFormatter(fmt)
        stream = logging.StreamHandler()
        stream.setFormatter(fmt)
        self._logger.addHandler(self._file_handler)
        self._logger.addHandler(stream)

    def debug(self, msg: str) -> None:
        self._logger.debug(msg)

    def info(self, msg: str) -> None:
        self._logger.info(msg)

    def warning(self, msg: str) -> None:
        self._logger.warning(msg)

    def error(self, msg: str) -> None:
        self._logger.error(msg)

    def close(self) -> None:
        for h in list(self._logger.handlers):
            h.close()
            self._logger.removeHandler(h)

    def __enter__(self) -> "PhotonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
