"""Phase timing + ingest-pipeline stage telemetry.

Parity target: reference ``Timed`` block timer (photon-lib util/Timed.scala,
used around every driver phase, e.g. estimators/GameEstimator.scala:341-364).

``StageStats``/``PipelineStats`` extend the same idea to the staged ingest
pipeline (io/pipeline.py): each host stage (decode / assemble / h2d) records
busy wall, time blocked on its input queue, time blocked on backpressure,
items and bytes through, and queue-depth samples — the numbers
``bench.py --pipeline-ab`` turns into per-stage occupancy columns and that
driver summaries surface next to the phase timers.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

logger = logging.getLogger("photon_tpu")


class Timed:
    """Context-manager timer that logs and records wall time per phase.

    ``records`` is process-global (driver summaries read it after the run),
    so it is guarded by a lock (phases can finish on pipeline worker
    threads) and cleared by ``reset()`` at driver entry — without the
    reset, a second driver invocation in the same process reported the
    previous run's stale phases in its summary. Each finished phase also
    lands as a trace span (obs/trace), so the run report sees every
    ``Timed`` block without callers changing anything.
    """

    records: Dict[str, float] = {}
    _records_lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        self.elapsed = 0.0

    @classmethod
    def records_lock(cls) -> threading.Lock:
        """Lock guarding ``records`` — hold it to snapshot consistently."""
        return cls._records_lock

    @classmethod
    def reset(cls) -> None:
        """New run: drop phase records (and the per-label pipeline
        telemetry that follows the same process-global pattern)."""
        with cls._records_lock:
            cls.records.clear()
        _pipeline_records.clear()

    def __enter__(self) -> "Timed":
        self._span = None
        try:
            from photon_tpu.obs.trace import tracer

            self._span = tracer().span(self.name)
            self._span.__enter__()
        except Exception:  # telemetry must never break the timed body
            self._span = None
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.monotonic() - self._t0
        if self._span is not None:
            self._span.__exit__(None, None, None)
        with Timed._records_lock:
            Timed.records[self.name] = self.elapsed
        logger.info("[timed] %s: %.3fs", self.name, self.elapsed)


@contextmanager
def timed(name: str) -> Iterator[None]:
    with Timed(name):
        yield


@dataclasses.dataclass
class StageStats:
    """Counters for ONE pipeline stage (decode / assemble / h2d / compute).

    busy_s:     wall spent doing the stage's work.
    wait_in_s:  wall blocked on the upstream queue (starved).
    wait_out_s: wall blocked putting downstream (backpressure).
    items/bytes: chunks and host bytes through the stage.
    depth_*:    output-queue depth sampled after each put — the direct
                backpressure observable (avg near the bound = downstream
                is the bottleneck; near 0 = this stage is).
    """

    name: str
    busy_s: float = 0.0
    wait_in_s: float = 0.0
    wait_out_s: float = 0.0
    items: int = 0
    bytes: int = 0
    depth_sum: int = 0
    depth_samples: int = 0
    depth_max: int = 0
    _lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add_busy(self, dt: float, nbytes: int = 0) -> None:
        with self._lock:
            self.busy_s += dt
            self.items += 1
            self.bytes += nbytes

    def add_wait_in(self, dt: float) -> None:
        with self._lock:
            self.wait_in_s += dt

    def add_wait_out(self, dt: float) -> None:
        with self._lock:
            self.wait_out_s += dt

    def sample_depth(self, depth: int) -> None:
        with self._lock:
            self.depth_sum += depth
            self.depth_samples += 1
            self.depth_max = max(self.depth_max, depth)

    @property
    def span_s(self) -> float:
        return self.busy_s + self.wait_in_s + self.wait_out_s

    @property
    def occupancy(self) -> float:
        """Fraction of the stage's lifetime spent working (vs blocked)."""
        span = self.span_s
        return self.busy_s / span if span > 0 else 0.0

    def as_dict(self) -> Dict[str, object]:
        return dict(
            name=self.name,
            busy_s=round(self.busy_s, 4),
            wait_in_s=round(self.wait_in_s, 4),
            wait_out_s=round(self.wait_out_s, 4),
            occupancy=round(self.occupancy, 4),
            items=self.items,
            bytes=self.bytes,
            queue_depth_avg=(
                round(self.depth_sum / self.depth_samples, 2)
                if self.depth_samples
                else 0.0
            ),
            queue_depth_max=self.depth_max,
        )


@dataclasses.dataclass
class PipelineStats:
    """Telemetry for one pipeline run: ordered stages + end-to-end wall."""

    stages: List[StageStats] = dataclasses.field(default_factory=list)
    wall_s: float = 0.0
    overlapped: bool = True

    def stage(self, name: str) -> StageStats:
        for s in self.stages:
            if s.name == name:
                return s
        s = StageStats(name)
        self.stages.append(s)
        return s

    def summary(self) -> Dict[str, object]:
        """The tracker-summary / bench-line shape: one entry per stage plus
        the overlap headline (sum of stage busy vs end-to-end wall — >1
        means host stages genuinely ran concurrently)."""
        busy = sum(s.busy_s for s in self.stages)
        return dict(
            overlapped=self.overlapped,
            wall_s=round(self.wall_s, 4),
            stage_busy_total_s=round(busy, 4),
            overlap_factor=(
                round(busy / self.wall_s, 3) if self.wall_s > 0 else 0.0
            ),
            stages={s.name: s.as_dict() for s in self.stages},
        )

    def log(self, prefix: str = "ingest-pipeline") -> None:
        logger.info("[timed] %s: %s", prefix, self.summary())

    def publish(self, label: str) -> None:
        """Flush this run's stage telemetry into the process-global metrics
        registry (obs/metrics) so the run report carries pipeline occupancy
        next to solver and cache metrics. Called once at pipeline finalize;
        the per-chunk hot path only ever touches the local dataclasses."""
        from photon_tpu.obs.metrics import registry

        reg = registry()
        reg.gauge("pipeline_wall_s", label=label).set(self.wall_s)
        reg.gauge("pipeline_overlapped", label=label).set(int(self.overlapped))
        busy = sum(s.busy_s for s in self.stages)
        reg.gauge("pipeline_overlap_factor", label=label).set(
            busy / self.wall_s if self.wall_s > 0 else 0.0
        )
        for s in self.stages:
            kw = dict(label=label, stage=s.name)
            reg.gauge("pipeline_stage_busy_s", **kw).set(s.busy_s)
            reg.gauge("pipeline_stage_starved_s", **kw).set(s.wait_in_s)
            reg.gauge("pipeline_stage_backpressured_s", **kw).set(
                s.wait_out_s
            )
            reg.gauge("pipeline_stage_occupancy", **kw).set(s.occupancy)
            reg.counter("pipeline_stage_items_total", **kw).inc(s.items)
            reg.counter("pipeline_stage_bytes_total", **kw).inc(s.bytes)
            reg.gauge("pipeline_stage_queue_depth_max", **kw).set(s.depth_max)
            reg.gauge("pipeline_stage_queue_depth_avg", **kw).set(
                s.depth_sum / s.depth_samples if s.depth_samples else 0.0
            )


# Most-recent pipeline telemetry per label, for driver summaries (the same
# process-global pattern as Timed.records).
_pipeline_records: Dict[str, PipelineStats] = {}


def record_pipeline(label: str, stats: PipelineStats) -> None:
    _pipeline_records[label] = stats


def pipeline_records() -> Dict[str, PipelineStats]:
    return _pipeline_records


def last_pipeline(label: str) -> Optional[PipelineStats]:
    return _pipeline_records.get(label)
