"""Phase timing.

Parity target: reference ``Timed`` block timer (photon-lib util/Timed.scala,
used around every driver phase, e.g. estimators/GameEstimator.scala:341-364).
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Dict, Iterator

logger = logging.getLogger("photon_tpu")


class Timed:
    """Context-manager timer that logs and records wall time per phase."""

    records: Dict[str, float] = {}

    def __init__(self, name: str):
        self.name = name
        self.elapsed = 0.0

    def __enter__(self) -> "Timed":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.monotonic() - self._t0
        Timed.records[self.name] = self.elapsed
        logger.info("[timed] %s: %.3fs", self.name, self.elapsed)


@contextmanager
def timed(name: str) -> Iterator[None]:
    with Timed(name):
        yield
