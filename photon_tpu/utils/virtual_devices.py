"""Force an n-device virtual CPU backend for mesh tests and dryruns.

The reference exercises distributed code without a cluster via
``SparkTestUtils.sparkTest`` (local[*] SparkSession per test,
photon-test-utils SparkTestUtils.scala:43-76). The JAX analogue is a
virtual multi-device CPU backend: ``--xla_force_host_platform_device_count``
plus pinning the platform to cpu. This helper is the single copy of that
dance, shared by ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``.

Environment gotcha: this image registers an 'axon' TPU-tunnel PJRT plugin at
interpreter startup and exports JAX_PLATFORMS=axon. A single touched axon
backend can hang every ``jax.devices()`` call, so the axon factory must be
dropped BEFORE any backend is initialized; env vars alone are too late
(the plugin hook read them at sitecustomize time).
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu_devices(n_devices: int) -> None:
    """Pin JAX to a CPU backend with ``n_devices`` virtual devices.

    Must run before any JAX backend is initialized (i.e. before the first
    ``jax.devices()`` / jitted execution in the process). Replaces any
    existing device-count flag so the requested count always wins.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"{_FLAG}=\d+", f"{_FLAG}={n_devices}", flags)
    else:
        flags = f"{flags} {_FLAG}={n_devices}"
    os.environ["XLA_FLAGS"] = flags.strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover - private API guard
        pass

    n_found = len(jax.devices())
    if n_found < n_devices:
        raise RuntimeError(
            f"requested {n_devices} virtual CPU devices but the backend has "
            f"{n_found} — a JAX backend was initialized before "
            "force_virtual_cpu_devices() ran (XLA reads the device-count "
            "flag only at backend creation). Call it first in the process."
        )
