"""Mid-training checkpoint/resume for coordinate descent.

The reference has NO mid-training checkpoint: its resume story is model-level
warm start only (load previous GAME model as the initial point,
GameTrainingDriver.scala:377-386, SURVEY.md §5 checkpoint/resume). This
module is the SURVEY §7.8 improvement: the full coordinate-descent state —
per-coordinate models, per-coordinate score arrays, residual total, iteration
counter, metric history — persists to host storage, so a preempted job
resumes mid-descent instead of restarting the λ-sweep entry.

Format: one ``step_<N>.npz`` with the flattened pytree leaves plus a pickled
treedef (all photon_tpu model classes are registered pytree nodes, so the
treedef round-trips typed objects — GameModel/FixedEffectModel/... come back
as themselves, not dict skeletons). bfloat16 leaves are stored as uint16
views (npz has no bf16). A ``LATEST`` file names the newest step;
``step_<N>`` files are self-contained so older steps remain loadable.

Single-host persistence (np.savez gathers sharded arrays). Multi-host
sharded checkpointing can swap in orbax behind the same API later.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_LATEST = "LATEST"


def _to_saveable(leaf):
    arr = np.asarray(leaf)
    if arr.dtype == jnp.bfloat16:
        return arr.view(np.uint16), "bfloat16"
    return arr, str(arr.dtype)


def save_checkpoint(directory: str, state: Any, step: int) -> str:
    """Persist a pytree ``state`` as step ``step``. Returns the file path."""
    os.makedirs(directory, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {}
    dtypes = []
    for i, leaf in enumerate(leaves):
        arr, dt = _to_saveable(leaf)
        arrays[f"leaf_{i}"] = arr
        dtypes.append(dt)
    payload = dict(
        treedef=pickle.dumps(treedef),
        dtypes=dtypes,
        num_leaves=len(leaves),
    )
    path = os.path.join(directory, f"step_{step}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __meta__=np.frombuffer(pickle.dumps(payload), np.uint8), **arrays)
    os.replace(tmp, path)  # atomic publish — no torn checkpoints on preemption
    latest_tmp = os.path.join(directory, _LATEST + ".tmp")
    with open(latest_tmp, "w") as f:
        f.write(str(step))
    os.replace(latest_tmp, os.path.join(directory, _LATEST))
    return path


def latest_step(directory: str) -> Optional[int]:
    p = os.path.join(directory, _LATEST)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        step = int(f.read().strip())
    if not os.path.exists(os.path.join(directory, f"step_{step}.npz")):
        return None
    return step


def load_checkpoint(directory: str, step: Optional[int] = None) -> Tuple[Any, int]:
    """Load a checkpoint (latest by default) back into typed pytree objects."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
    with np.load(os.path.join(directory, f"step_{step}.npz"), allow_pickle=True) as z:
        payload = pickle.loads(z["__meta__"].tobytes())
        treedef = pickle.loads(payload["treedef"])
        leaves = []
        for i, dt in enumerate(payload["dtypes"]):
            arr = z[f"leaf_{i}"]
            if dt == "bfloat16":
                arr = jnp.asarray(arr.view(np.uint16)).view(jnp.bfloat16)
            elif arr.ndim == 0 and arr.dtype == object:
                arr = arr.item()
            elif arr.ndim == 0 and arr.dtype.kind in ("U", "S", "b"):
                arr = arr.item()  # strings / bools round-trip as themselves
            elif arr.ndim == 0 and arr.dtype in (np.float64, np.int64):
                # Host python scalars (metric values, counters) round-trip as
                # scalars — jnp would silently downcast float64 with x64 off.
                arr = arr.item()
            else:
                # Device arrays on save → device arrays on restore (solvers
                # rely on jnp semantics like .at[]).
                arr = jnp.asarray(arr)
            leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves), step
