"""Mid-training checkpoint/resume for coordinate descent.

The reference has NO mid-training checkpoint: its resume story is model-level
warm start only (load previous GAME model as the initial point,
GameTrainingDriver.scala:377-386, SURVEY.md §5 checkpoint/resume). This
module is the SURVEY §7.8 improvement: the full coordinate-descent state —
per-coordinate models, per-coordinate score arrays, residual total, iteration
counter, metric history — persists to host storage, so a preempted job
resumes mid-descent instead of restarting the λ-sweep entry.

Format: one ``step_<N>.npz`` holding the array leaves plus a **declarative
JSON manifest** describing the structure: containers, literals, enums by
registry key + value, and framework objects by REGISTRY KEY + field names
(+ per-array shape/dtype for validation). No pickled code objects anywhere —
loading a checkpoint can only construct classes explicitly allow-listed in
``_REGISTRY``, so an untrusted checkpoint directory cannot execute arbitrary
code (pickle's failure mode), and renaming/moving a class doesn't strand old
checkpoints as long as its registry key stays stable.

bfloat16 leaves are stored as uint16 views (npz has no bf16). A ``LATEST``
file names the newest step; ``step_<N>`` files are self-contained so older
steps remain loadable.

Single-host persistence (np.savez gathers sharded arrays). Multi-host
sharded checkpointing can swap in orbax behind the same API later.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import json
import logging
import os
import zipfile
from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.utils import faults, resources

logger = logging.getLogger(__name__)

_LATEST = "LATEST"
_FORMAT_VERSION = 2

CHECKPOINT_KEEP_LAST_ENV = "PHOTON_TPU_CHECKPOINT_KEEP_LAST"

# Checkpoints sit at the top of the degradation priority (they ARE the model
# artifact), so their ENOSPC policy is the aggressive one: prune, retry.
_DISK_GUARD = resources.DiskBudgetGuard("checkpoint.io")


class LegacyCheckpointError(ValueError):
    """Raised for v1 (pickle-era) checkpoints. Typed so resume sites can
    restart-from-scratch on upgrades without string-matching messages
    (which would misclassify genuinely corrupt v2 checkpoints)."""

# ---------------------------------------------------------------------------
# Registry: stable key ↔ class. Keys are the durable identity — keep them
# unchanged across refactors/renames.
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Type] = {}
_KEY_OF: Dict[Type, str] = {}


def register_checkpoint_node(key: str, cls: Type) -> None:
    """Allow-list ``cls`` for checkpoint (de)serialization under ``key``.
    Dataclasses round-trip by field names; Enums by value."""
    _REGISTRY[key] = cls
    _KEY_OF[cls] = key


def _register_builtin_nodes() -> None:
    from photon_tpu.algorithm.random_effect import RandomEffectTrackerStats
    from photon_tpu.models.coefficients import Coefficients
    from photon_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        ProjectedRandomEffectModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import GeneralizedLinearModel
    from photon_tpu.optim.common import OptimizeResult, OptimizerConfig
    from photon_tpu.types import OptimizerType, TaskType, VarianceComputationType

    for key, cls in {
        "game_model": GameModel,
        "fixed_effect_model": FixedEffectModel,
        "random_effect_model": RandomEffectModel,
        "projected_random_effect_model": ProjectedRandomEffectModel,
        "glm": GeneralizedLinearModel,
        "coefficients": Coefficients,
        "optimize_result": OptimizeResult,
        "optimizer_config": OptimizerConfig,
        "re_tracker_stats": RandomEffectTrackerStats,
        "task_type": TaskType,
        "optimizer_type": OptimizerType,
        "variance_type": VarianceComputationType,
    }.items():
        register_checkpoint_node(key, cls)


# ---------------------------------------------------------------------------
# Encode / decode
# ---------------------------------------------------------------------------


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) or (
        isinstance(x, np.generic) and not isinstance(x, (np.str_, np.bytes_))
    )


def _encode(obj: Any, arrays: list) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"t": "lit", "v": obj}
    if _is_array(obj):
        arr = np.asarray(obj)
        dt = "bfloat16" if arr.dtype == jnp.bfloat16 else str(arr.dtype)
        if dt == "bfloat16":
            arr = arr.view(np.uint16)
        idx = len(arrays)
        arrays.append(arr)
        # Scalar numpy values re-materialize as python scalars on load when
        # they were np.generic (counters, metrics) — tagged separately.
        kind = "scalar" if obj.__class__.__module__ == "numpy" and arr.ndim == 0 else "array"
        return {
            "t": kind, "i": idx, "shape": list(arr.shape), "dtype": dt,
            # Payload digest: shape/dtype validation catches structural
            # corruption, but bit-rot inside the data blocks deserializes
            # fine and would silently poison a resume. Verified on load
            # (when present — older checkpoints without it still load).
            "sha256": hashlib.sha256(
                np.ascontiguousarray(arr).tobytes()
            ).hexdigest(),
        }
    if isinstance(obj, (list, tuple)):
        return {
            "t": "tuple" if isinstance(obj, tuple) else "list",
            "items": [_encode(x, arrays) for x in obj],
        }
    if isinstance(obj, dict):
        bad = [k for k in obj if not isinstance(k, str)]
        if bad:
            raise TypeError(
                f"checkpoint dicts need string keys; got {type(bad[0]).__name__}"
            )
        return {"t": "dict", "items": {k: _encode(v, arrays) for k, v in obj.items()}}
    if isinstance(obj, enum.Enum):
        key = _KEY_OF.get(type(obj))
        if key is None:
            raise TypeError(
                f"enum {type(obj).__name__} is not checkpoint-registered; "
                "call register_checkpoint_node"
            )
        return {"t": "enum", "cls": key, "v": obj.value}
    key = _KEY_OF.get(type(obj))
    if key is not None and dataclasses.is_dataclass(obj):
        return {
            "t": "node",
            "cls": key,
            "fields": {
                f.name: _encode(getattr(obj, f.name), arrays)
                for f in dataclasses.fields(obj)
            },
        }
    raise TypeError(
        f"cannot checkpoint object of type {type(obj).__name__}: not a "
        "primitive/array/container and not registered via "
        "register_checkpoint_node"
    )


def _decode(spec: Any, z) -> Any:
    t = spec["t"]
    if t == "lit":
        return spec["v"]
    if t in ("array", "scalar"):
        arr = z[f"leaf_{spec['i']}"]
        if list(arr.shape) != spec["shape"] or (
            spec["dtype"] != "bfloat16" and str(arr.dtype) != spec["dtype"]
        ):
            raise ValueError(
                f"checkpoint corrupt: leaf {spec['i']} is "
                f"{arr.dtype}{arr.shape}, manifest says "
                f"{spec['dtype']}{tuple(spec['shape'])}"
            )
        want = spec.get("sha256")
        if want is not None:
            got = hashlib.sha256(
                np.ascontiguousarray(arr).tobytes()
            ).hexdigest()
            if got != want:
                raise ValueError(
                    f"checkpoint corrupt: leaf {spec['i']} sha256 mismatch "
                    f"(payload bit-rot): {got[:12]} != manifest {want[:12]}"
                )
        if spec["dtype"] == "bfloat16":
            return jnp.asarray(arr.view(np.uint16)).view(jnp.bfloat16)
        if t == "scalar":
            return arr[()].item()
        # Device arrays on save → device arrays on restore (solvers rely on
        # jnp semantics like .at[]).
        return jnp.asarray(arr)
    if t == "list":
        return [_decode(x, z) for x in spec["items"]]
    if t == "tuple":
        return tuple(_decode(x, z) for x in spec["items"])
    if t == "dict":
        return {k: _decode(v, z) for k, v in spec["items"].items()}
    if t == "enum":
        cls = _REGISTRY.get(spec["cls"])
        if cls is None:
            raise ValueError(f"unknown checkpoint enum key {spec['cls']!r}")
        return cls(spec["v"])
    if t == "node":
        cls = _REGISTRY.get(spec["cls"])
        if cls is None:
            raise ValueError(
                f"unknown checkpoint node key {spec['cls']!r} — register it "
                "with register_checkpoint_node"
            )
        fields = {k: _decode(v, z) for k, v in spec["fields"].items()}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(fields) - known
        if unknown:
            raise ValueError(
                f"checkpoint field(s) {sorted(unknown)} not on "
                f"{cls.__name__} — incompatible schema change"
            )
        return cls(**fields)
    raise ValueError(f"unknown checkpoint tag {t!r}")


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def _write_step(path: str, manifest: dict, arrays: list) -> None:
    """Atomically write one step file. Any failure — including the injected
    ``enospc`` at the ``checkpoint.io`` hook — removes the partial tmp file
    before propagating: a failed save must not eat the very space a retry
    (or a later step) needs."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            # ``enospc`` rules fire here, after the tmp exists but before
            # its data does — the worst place a real full disk bites.
            _DISK_GUARD.check()
            np.savez(
                f,
                __manifest__=np.frombuffer(
                    json.dumps(manifest).encode(), np.uint8
                ),
                **{f"leaf_{i}": a for i, a in enumerate(arrays)},
            )
            # Durability before visibility: without the fsync, a machine
            # crash (not just process preemption) can publish a rename whose
            # DATA blocks never hit disk — a torn file at the final name.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish — no torn checkpoints
    except BaseException:
        _DISK_GUARD.cleanup(tmp)
        raise


def prune_checkpoints(directory: str, keep_last: Optional[int]) -> int:
    """Delete the oldest ``step_<N>.npz`` files so at most ``keep_last``
    remain (newest kept). Best-effort; returns how many were removed and
    counts them in ``checkpoint_pruned_total``."""
    if keep_last is None or keep_last < 1:
        return 0
    steps = _scan_steps(directory)
    removed = 0
    for s in steps[:-keep_last] if len(steps) > keep_last else []:
        try:
            os.unlink(os.path.join(directory, f"step_{s}.npz"))
            removed += 1
        except OSError:
            pass
    if removed:
        try:
            from photon_tpu.obs import registry

            registry().counter("checkpoint_pruned_total").inc(removed)
        except Exception:
            pass
    return removed


def save_checkpoint(
    directory: str, state: Any, step: int, keep_last: Optional[int] = None
) -> str:
    """Persist ``state`` (containers + arrays + registered framework
    objects) as step ``step``. Returns the file path.

    ``keep_last`` (or the ``PHOTON_TPU_CHECKPOINT_KEEP_LAST`` env var when
    None) caps how many step files survive after a successful publish.
    ENOSPC during the write prunes down to the single newest older step and
    retries once before giving up — checkpoints outrank everything else in
    the degradation priority, so they reclaim their own disk first."""
    if not _REGISTRY:
        _register_builtin_nodes()
    if keep_last is None:
        env = os.environ.get(CHECKPOINT_KEEP_LAST_ENV, "").strip()
        keep_last = int(env) if env else None
    os.makedirs(directory, exist_ok=True)
    arrays: list = []
    manifest = {"version": _FORMAT_VERSION, "root": _encode(state, arrays)}
    path = os.path.join(directory, f"step_{step}.npz")
    # Fault hook: a ``torn`` rule simulates a machine crash that published
    # the rename but not the data blocks — a truncated file at the FINAL
    # name, which resumable loads must skip (see load_checkpoint). A
    # ``kill``/error rule fires before anything is written.
    rule = faults.injector().fire("checkpoint.save")
    if rule is not None:
        if rule.kind == "kill":
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        if rule.kind == "torn":
            with open(path, "wb") as f:
                f.write(b"PK\x03\x04torn-checkpoint")
            raise faults.PermanentInjectedFault(
                f"injected torn checkpoint at {path}"
            )
        raise faults.exception_for(rule, "checkpoint.save")
    try:
        _write_step(path, manifest, arrays)
    except OSError as exc:
        if not _DISK_GUARD.record(exc):
            raise
        pruned = prune_checkpoints(directory, keep_last=1)
        logger.warning(
            "disk full writing checkpoint step %d; pruned %d older step(s) "
            "and retrying once: %s", step, pruned, exc,
        )
        _write_step(path, manifest, arrays)  # second failure propagates
    latest_tmp = os.path.join(directory, _LATEST + ".tmp")
    try:
        with open(latest_tmp, "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        os.replace(latest_tmp, os.path.join(directory, _LATEST))
    except BaseException:
        _DISK_GUARD.cleanup(latest_tmp)
        raise
    prune_checkpoints(directory, keep_last)
    # Post-publish hook: the ``ci.sh faults`` kill-and-resume smoke SIGKILLs
    # here, right after a step becomes durable — the worst legitimate moment.
    faults.check("checkpoint.after_save")
    return path


def _scan_steps(directory: str) -> list:
    """Step numbers of every self-contained step_<N>.npz present."""
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name.endswith(".npz"):
            try:
                steps.append(int(name[len("step_"):-len(".npz")]))
            except ValueError:
                continue
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest resumable step. The LATEST pointer is an optimization, not
    the source of truth: when it is missing, torn (garbage content), or
    names a step file that does not exist, fall back to scanning the
    self-contained ``step_<N>.npz`` files — a half-written pointer must
    never strand an otherwise intact checkpoint directory."""
    p = os.path.join(directory, _LATEST)
    if os.path.exists(p):
        with open(p) as f:
            raw = f.read().strip()
        try:
            step = int(raw)
        except ValueError:
            step = None  # torn/garbage pointer: recover by scan below
        if step is not None and os.path.exists(
            os.path.join(directory, f"step_{step}.npz")
        ):
            return step
    if not os.path.isdir(directory):
        return None
    steps = _scan_steps(directory)
    return steps[-1] if steps else None


def load_checkpoint(directory: str, step: Optional[int] = None) -> Tuple[Any, int]:
    """Load a checkpoint back into typed objects. Only JSON + numpy arrays
    are read — no pickle, no code execution.

    With an explicit ``step``, a corrupt file raises (the caller asked for
    that exact step). With ``step=None`` the load is RESUME-ROBUST: it walks
    the available steps newest→oldest and skips unreadable ones (truncated
    npz from a machine crash mid-``save_checkpoint``, missing manifest,
    shape-mangled leaves) with a warning and a
    ``checkpoint_corrupt_skipped_total`` count, so a torn newest step never
    strands the run — it resumes one step earlier. Raises
    ``FileNotFoundError`` when no step exists, :class:`LegacyCheckpointError`
    when the only candidates are v1/pickle files, or the last decode error
    when every candidate is corrupt."""
    if not _REGISTRY:
        _register_builtin_nodes()
    if step is not None:
        return _load_step(directory, step)
    newest = latest_step(directory)
    if newest is None:
        raise FileNotFoundError(f"no checkpoint under {directory}")
    candidates = sorted(set(_scan_steps(directory)) | {newest}, reverse=True)
    legacy_exc: Optional[LegacyCheckpointError] = None
    last_exc: Optional[Exception] = None
    for s in candidates:
        try:
            return _load_step(directory, s)
        except LegacyCheckpointError as exc:
            legacy_exc = exc
        except (ValueError, OSError, KeyError, EOFError, zipfile.BadZipFile) as exc:
            last_exc = exc
            logger.warning(
                "skipping unreadable checkpoint step %d under %s: %s",
                s, directory, exc,
            )
            try:
                from photon_tpu.obs import registry

                registry().counter("checkpoint_corrupt_skipped_total").inc()
            except Exception:
                pass
    if legacy_exc is not None:
        raise legacy_exc
    assert last_exc is not None
    raise last_exc


def _load_step(directory: str, step: int) -> Tuple[Any, int]:
    # allow_pickle stays False (numpy default): object arrays are rejected.
    path = os.path.join(directory, f"step_{step}.npz")
    try:
        z_ctx = np.load(path)
    except (ValueError, OSError) as exc:
        if "pickle" in str(exc):  # a v1 pickle file, not an npz at all
            raise LegacyCheckpointError(
                f"legacy (pickle-based) checkpoint at {path} — not loadable "
                "by this version; retrain or re-save"
            ) from exc
        raise
    with z_ctx as z:
        if "__manifest__" not in z:
            raise LegacyCheckpointError(
                "legacy (pickle-based) checkpoint format — not loadable by "
                "this version; retrain or re-save"
            )
        manifest = json.loads(z["__manifest__"].tobytes().decode())
        if manifest.get("version") != _FORMAT_VERSION:
            raise ValueError(
                f"unsupported checkpoint version {manifest.get('version')}"
            )
        return _decode(manifest["root"], z), step
