"""Lifecycle event bus.

Parity target: reference ``EventEmitter`` trait + listener registry
(photon-client event/EventEmitter.scala:24-80) and the event types
(event/Event.scala:28-70: PhotonSetupEvent, TrainingStartEvent,
TrainingFinishEvent, PhotonOptimizationLogEvent). Listeners can be
registered by dotted class path, mirroring the reference's
class-name-from-CLI registration (Driver.scala:99-108).
"""

from __future__ import annotations

import dataclasses
import importlib
import logging
import threading
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("photon_tpu")


@dataclasses.dataclass(frozen=True)
class Event:
    name: str
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)


def setup_event(**kw) -> Event:
    return Event("PhotonSetupEvent", kw)


def training_start_event(**kw) -> Event:
    return Event("TrainingStartEvent", kw)


def training_finish_event(**kw) -> Event:
    return Event("TrainingFinishEvent", kw)


def optimization_log_event(**kw) -> Event:
    return Event("PhotonOptimizationLogEvent", kw)


Listener = Callable[[Event], None]


class EventEmitter:
    """Thread-safe listener registry + emit."""

    def __init__(self):
        self._listeners: List[Listener] = []
        self._lock = threading.Lock()

    def register(self, listener: Listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_by_name(self, dotted_path: str) -> None:
        """Register a listener class/function by module path
        ('pkg.module:attr' or 'pkg.module.attr')."""
        if ":" in dotted_path:
            mod, attr = dotted_path.split(":", 1)
        else:
            mod, _, attr = dotted_path.rpartition(".")
        obj = getattr(importlib.import_module(mod), attr)
        listener = obj() if isinstance(obj, type) else obj
        self.register(listener)

    def clear(self) -> None:
        with self._lock:
            self._listeners.clear()

    def emit(self, event: Event) -> None:
        """Deliver to every listener. Each call is isolated: one raising
        listener is logged (with traceback) and the rest still receive the
        event — a misbehaving observer must never abort the run or starve
        later listeners of lifecycle events."""
        with self._lock:
            listeners = list(self._listeners)
        for l in listeners:
            try:
                l(event)
            except Exception:
                logger.exception(
                    "event listener %r failed on %s (delivery continues)",
                    l, event.name,
                )
