"""GAME scoring driver.

Parity target: reference ``GameScoringDriver`` (photon-client
cli/game/scoring/GameScoringDriver.scala:39-284): feature maps → read data →
load GameModel → GameTransformer → save ScoringResultAvro (+ optional
evaluation).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict

import jax.numpy as jnp
import numpy as np

from photon_tpu.cli.common import (
    add_common_args,
    parse_feature_shard_config,
    setup_logging,
)
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.estimators.game_transformer import GameTransformer
from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
from photon_tpu.io.data_reader import read_merged
from photon_tpu.io.model_io import (
    load_game_model,
    model_re_types,
    read_model_metadata,
)
from photon_tpu.io.scores import save_scores


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-scoring")
    add_common_args(p)
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--model-artifacts-dir", default=None,
                   help="dir holding index-map-*.json / entity-index-*.json "
                        "(defaults to the training output dir = parent of model dir)")
    p.add_argument("--evaluators", nargs="*", default=[])
    p.add_argument("--model-id", default="game-model")
    p.add_argument("--stream-ingest-chunk-rows", type=int, default=0,
                   help="score through the chunked streaming reader: host "
                        "memory bounded by one chunk of features (scores/"
                        "labels/ids accumulate — they are O(n) scalars); "
                        "chunks pad to a multiple of this (sparse nnz "
                        "widths bucket to powers of two) so the scoring "
                        "program compiles for a handful of shapes, not one "
                        "per chunk")
    p.add_argument("--ingest-queue-depth", type=int, default=None,
                   help="bound (in chunks) on each inter-stage pipeline "
                        "queue (default: measured double-buffering depth, "
                        "io/pipeline.py)")
    p.add_argument("--serial-ingest", action="store_true",
                   help="run the ingest stages inline on the consumer "
                        "thread instead of on pipeline worker threads "
                        "(the pre-pipeline behavior; the bench A/B control)")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted paths of event listener callables")
    p.add_argument("--event-listener", action="append", default=[],
                   dest="event_listener",
                   help="register one event listener by path "
                        "('pkg.module:attr'); repeatable")
    p.add_argument("--telemetry-out", default=None,
                   help="write the unified run report (spans + metrics + "
                        "ingest-pipeline occupancy) as schema-stable JSONL "
                        "to this path")
    from photon_tpu.cli.common import add_active_set_args, add_out_of_core_args

    add_active_set_args(p)
    add_out_of_core_args(p)
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    if getattr(args, "re_active_set", False):
        import logging

        logging.getLogger(__name__).warning(
            "--re-active-set is a no-op for the scoring driver (nothing is "
            "trained); it only affects GAME training"
        )
    if getattr(args, "re_device_budget_mb", None):
        import logging

        logging.getLogger(__name__).warning(
            "--re-device-budget-mb is a no-op for the scoring driver "
            "(nothing is trained); it only affects GAME training"
        )
    from photon_tpu.obs import begin_run, finalize_run_report
    from photon_tpu.utils.events import (
        EventEmitter,
        setup_event,
        training_finish_event,
    )

    begin_run()  # fresh spans / metrics / phase records for THIS run
    emitter = EventEmitter()
    for name in list(getattr(args, "event_listeners", [])) + list(
        getattr(args, "event_listener", [])
    ):
        emitter.register_by_name(name)
    emitter.emit(
        setup_event(driver="game_scoring", model_input_dir=args.model_input_dir)
    )
    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))

    artifacts = args.model_artifacts_dir or os.path.dirname(
        args.model_input_dir.rstrip("/")
    )
    index_maps = {}
    for shard in shard_configs:
        index_maps[shard] = IndexMap.load(
            os.path.join(artifacts, f"index-map-{shard}.json")
        )
    entity_indexes: Dict[str, EntityIndex] = {}
    re_types = model_re_types(read_model_metadata(args.model_input_dir))
    for re_type in re_types:
        path = os.path.join(artifacts, f"entity-index-{re_type}.json")
        if os.path.exists(path):
            entity_indexes[re_type] = EntityIndex.load(path)

    model = load_game_model(args.model_input_dir, index_maps, entity_indexes)

    from photon_tpu.cli.common import parse_input_column_names, resolve_input_paths
    from photon_tpu.utils.io_utils import process_output_dir

    process_output_dir(args.output_dir, args.override_output_dir)
    column_names = parse_input_column_names(
        getattr(args, "input_column_names", None)
    )
    read_kwargs = dict(
        entity_id_columns={rt: rt for rt in re_types},
        entity_indexes=entity_indexes, intern_new_entities=False,
        column_names=column_names,
    )

    suite = None
    if args.evaluators:
        num_entities = {k: len(v) for k, v in entity_indexes.items()}
        suite = EvaluationSuite(
            [EvaluatorSpec.parse(e) for e in args.evaluators], num_entities
        )

    chunk_rows = int(getattr(args, "stream_ingest_chunk_rows", 0) or 0)
    if chunk_rows > 0:
        # Streaming: decode → assemble → h2d run as pipeline stages
        # (io/pipeline.py; worker threads + bounded queues unless
        # --serial-ingest) overlapping the jitted scorer via async dispatch.
        # Feature chunks are scored and dropped; only the O(n)-scalar
        # columns (scores/labels/weights/uids/entity ids) accumulate.
        # Chunks pad to a chunk_rows multiple so the jitted scoring program
        # compiles for at most a couple of shapes.
        import time

        from photon_tpu.data.game_data import GameBatch
        from photon_tpu.io.pipeline import (
            DEFAULT_QUEUE_DEPTH,
            stream_device_batches,
        )
        from photon_tpu.utils.timed import PipelineStats

        transformer = GameTransformer(model, None)
        acc: Dict[str, list] = {
            "scores": [], "label": [], "weight": [], "uid": [],
            **{rt: [] for rt in re_types},
        }
        overlap = not getattr(args, "serial_ingest", False)
        stats = PipelineStats(overlapped=overlap)
        compute = stats.stage("compute")
        gen = stream_device_batches(
            resolve_input_paths(args), shard_configs, index_maps,
            chunk_rows=chunk_rows, pad_rows_to=chunk_rows,
            depth=getattr(args, "ingest_queue_depth", None)
            or DEFAULT_QUEUE_DEPTH,
            overlap=overlap, telemetry_label="scoring-ingest", stats=stats,
            **read_kwargs,
        )
        while True:
            # Only the STREAM can be "unavailable" — scoring errors must
            # surface as themselves, not as advice to drop the flag.
            try:
                chunk = next(gen)
            except StopIteration:
                break
            except (RuntimeError, ValueError) as exc:
                raise SystemExit(
                    f"streaming ingest unavailable: {exc}; drop "
                    "--stream-ingest-chunk-rows to use the slurping reader"
                ) from exc
            n, b = chunk.n, chunk.batch
            t0 = time.perf_counter()
            s = transformer.transform(b)
            scores_np = np.asarray(s)  # blocks: device compute wall
            compute.add_busy(time.perf_counter() - t0)
            acc["scores"].append(scores_np[:n])
            acc["label"].append(np.asarray(b.label)[:n])
            acc["weight"].append(np.asarray(b.weight)[:n])
            # uids were renumbered globally by the assemble stage, so
            # scores.avro matches the slurp path's UniqueSampleId sequence.
            acc["uid"].append(np.asarray(b.uid)[:n])
            for rt in re_types:
                acc[rt].append(np.asarray(b.entity_ids[rt])[:n])
        if not acc["scores"]:
            raise SystemExit("streaming ingest read zero data blocks")
        scores = np.concatenate(acc["scores"])
        labels = np.concatenate(acc["label"])
        weights = np.concatenate(acc["weight"])
        uid_arr = np.concatenate(acc["uid"])
        metrics = None
        if suite is not None:
            eval_batch = GameBatch(
                label=jnp.asarray(labels),
                offset=jnp.zeros(len(labels), jnp.float32),
                weight=jnp.asarray(weights),
                features={},
                entity_ids={rt: jnp.asarray(np.concatenate(acc[rt]))
                            for rt in re_types},
            )
            metrics = suite.evaluate_scores(jnp.asarray(scores), eval_batch)
        pipeline_summary = stats.summary()
    else:
        batch, _, _ = read_merged(
            resolve_input_paths(args), shard_configs, index_maps=index_maps,
            **read_kwargs,
        )
        transformer = GameTransformer(model, suite)
        scores = np.asarray(transformer.transform(batch))
        labels = np.asarray(batch.label)
        weights = np.asarray(batch.weight)
        uid_arr = np.asarray(batch.uid)
        metrics = transformer.last_metrics if suite is not None else None
        pipeline_summary = None

    os.makedirs(args.output_dir, exist_ok=True)
    save_scores(
        os.path.join(args.output_dir, "scores.avro"),
        scores,
        args.model_id,
        uids=[str(int(u)) for u in uid_arr],
        labels=labels,
        weights=weights,
    )
    out = {"numScored": int(scores.shape[0])}
    if pipeline_summary is not None:
        out["ingestPipeline"] = pipeline_summary
    if metrics is not None:
        out["metrics"] = metrics
        with open(os.path.join(args.output_dir, "scoring-metrics.json"), "w") as f:
            json.dump(metrics, f, indent=2)
    emitter.emit(training_finish_event(num_scored=out["numScored"]))
    finalize_run_report(
        "game_scoring", path=args.telemetry_out, emitter=emitter
    )
    return out


def main(argv=None):
    args = build_parser().parse_args(argv)
    print(json.dumps(run(args)))


if __name__ == "__main__":
    main()
