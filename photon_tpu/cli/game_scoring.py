"""GAME scoring driver.

Parity target: reference ``GameScoringDriver`` (photon-client
cli/game/scoring/GameScoringDriver.scala:39-284): feature maps → read data →
load GameModel → GameTransformer → save ScoringResultAvro (+ optional
evaluation).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict

import numpy as np

from photon_tpu.cli.common import (
    add_common_args,
    parse_feature_shard_config,
    setup_logging,
)
from photon_tpu.data.index_map import EntityIndex, IndexMap
from photon_tpu.estimators.game_transformer import GameTransformer
from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
from photon_tpu.io.data_reader import read_merged
from photon_tpu.io.model_io import METADATA_FILE, load_game_model
from photon_tpu.io.scores import save_scores


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-scoring")
    add_common_args(p)
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--model-artifacts-dir", default=None,
                   help="dir holding index-map-*.json / entity-index-*.json "
                        "(defaults to the training output dir = parent of model dir)")
    p.add_argument("--evaluators", nargs="*", default=[])
    p.add_argument("--model-id", default="game-model")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))

    artifacts = args.model_artifacts_dir or os.path.dirname(
        args.model_input_dir.rstrip("/")
    )
    index_maps = {}
    for shard in shard_configs:
        index_maps[shard] = IndexMap.load(
            os.path.join(artifacts, f"index-map-{shard}.json")
        )
    entity_indexes: Dict[str, EntityIndex] = {}
    with open(os.path.join(args.model_input_dir, METADATA_FILE)) as f:
        meta = json.load(f)
    re_types = [
        info["reType"] for info in meta["coordinates"].values() if info["type"] == "random"
    ]
    for re_type in re_types:
        path = os.path.join(artifacts, f"entity-index-{re_type}.json")
        if os.path.exists(path):
            entity_indexes[re_type] = EntityIndex.load(path)

    model = load_game_model(args.model_input_dir, index_maps, entity_indexes)

    from photon_tpu.cli.common import parse_input_column_names, resolve_input_paths
    from photon_tpu.utils.io_utils import process_output_dir

    process_output_dir(args.output_dir, args.override_output_dir)
    batch, _, _ = read_merged(
        resolve_input_paths(args), shard_configs, index_maps=index_maps,
        entity_id_columns={rt: rt for rt in re_types},
        entity_indexes=entity_indexes, intern_new_entities=False,
        column_names=parse_input_column_names(
            getattr(args, "input_column_names", None)
        ),
    )

    suite = None
    if args.evaluators:
        num_entities = {k: len(v) for k, v in entity_indexes.items()}
        suite = EvaluationSuite(
            [EvaluatorSpec.parse(e) for e in args.evaluators], num_entities
        )
    transformer = GameTransformer(model, suite)
    scores = transformer.transform(batch)

    os.makedirs(args.output_dir, exist_ok=True)
    save_scores(
        os.path.join(args.output_dir, "scores.avro"),
        np.asarray(scores),
        args.model_id,
        uids=[str(int(u)) for u in np.asarray(batch.uid)],
        labels=np.asarray(batch.label),
        weights=np.asarray(batch.weight),
    )
    out = {"numScored": int(scores.shape[0])}
    if suite is not None:
        out["metrics"] = transformer.last_metrics
        with open(os.path.join(args.output_dir, "scoring-metrics.json"), "w") as f:
            json.dump(transformer.last_metrics, f, indent=2)
    return out


def main(argv=None):
    args = build_parser().parse_args(argv)
    print(json.dumps(run(args)))


if __name__ == "__main__":
    main()
