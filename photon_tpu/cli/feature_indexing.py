"""Feature indexing driver: scan feature bags → persistent index map stores.

Parity target: reference ``FeatureIndexingDriver``
(photon-client index/FeatureIndexingDriver.scala:42-330): distinct feature
scan (+intercept), hash-partitioned PalDB store files consumed later by
PalDBIndexMapLoader. Here the store is either JSON (small maps) or the
native mmap store (photon_tpu.data.native_index) when --num-partitions > 0.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict

from photon_tpu.cli.common import parse_feature_shard_config, setup_logging
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io.data_reader import _feature_key, read_avro_rows


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("feature-indexing")
    p.add_argument("--input-paths", nargs="+", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard-configurations", nargs="+", default=["name=global"])
    p.add_argument("--num-partitions", type=int, default=0,
                   help=">0 writes the partitioned native mmap store instead of JSON")
    p.add_argument("--verbose", action="store_true")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))
    rows = read_avro_rows(args.input_paths)
    os.makedirs(args.output_dir, exist_ok=True)
    out = {}
    for shard, cfg in shard_configs.items():
        keys = set()
        for row in rows:
            for bag in cfg.feature_bags:
                for f in row.get(bag) or []:
                    keys.add(_feature_key(f))
        imap = IndexMap.build(keys, add_intercept=cfg.has_intercept)
        if args.num_partitions > 0:
            from photon_tpu.data.native_index import NativeIndexMapBuilder

            store_dir = os.path.join(args.output_dir, f"index-store-{shard}")
            NativeIndexMapBuilder(store_dir, args.num_partitions).build(imap)
        else:
            imap.save(os.path.join(args.output_dir, f"index-map-{shard}.json"))
        out[shard] = len(imap)
    with open(os.path.join(args.output_dir, "feature-indexing-summary.json"), "w") as f:
        json.dump(out, f)
    return out


def main(argv=None):
    args = build_parser().parse_args(argv)
    print(json.dumps(run(args)))


if __name__ == "__main__":
    main()
