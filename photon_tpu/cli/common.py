"""Shared CLI plumbing: config-string grammars + platform setup.

Parity target: the reference's declarative scopt layer (photon-client
io/scopt/ScoptParserHelpers.scala compound-argument grammar, e.g.
``name=global,feature.shard=shardA,optimizer=LBFGS,reg.weights=0.1|1|10``
from README.md:293-296) and per-driver parsers (io/scopt/game/*.scala).
Implemented over argparse: each compound argument is a comma-separated
key=value list; multi-values use ``|``.
"""

from __future__ import annotations

import argparse
import logging
from typing import Dict, List, Optional

from photon_tpu.estimators.config import (
    FixedEffectCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.io.data_reader import FeatureShardConfig
from photon_tpu.types import OptimizerType, TaskType


def setup_logging(verbose: bool = False) -> None:
    logging.basicConfig(
        level=logging.DEBUG if verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )


# Re-exported for drivers (the implementation lives in utils so algorithm
# code can poll shutdown_requested without importing the CLI layer).
from photon_tpu.utils.shutdown import (  # noqa: E402,F401
    GracefulShutdown,
    handle_termination,
    shutdown_requested,
)


def parse_kv(spec: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad key=value element {part!r} in {spec!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_feature_shard_config(spec: str) -> Dict[str, FeatureShardConfig]:
    """``name=shardA,feature.bags=features|songFeatures,intercept=true``"""
    kv = parse_kv(spec)
    name = kv.pop("name")
    bags = kv.pop("feature.bags", "features").split("|")
    intercept = kv.pop("intercept", "true").lower() != "false"
    if kv:
        raise ValueError(f"unknown feature-shard keys: {sorted(kv)}")
    return {name: FeatureShardConfig(feature_bags=bags, has_intercept=intercept)}


def parse_coordinate_config(spec: str):
    """Reference coordinate-configurations grammar:

    ``name=global,feature.shard=shardA,optimizer=LBFGS,reg.weights=0.1|1|10``
    plus for random effects: ``random.effect.type=userId`` and optional
    ``active.data.upper.bound= / active.data.lower.bound= /
    features.to.samples.ratio=``. Additional keys: ``max.iter=``, ``tol=``,
    ``reg.alpha=`` (elastic net), ``down.sampling.rate=``.
    """
    kv = parse_kv(spec)
    name = kv.pop("name")
    shard = kv.pop("feature.shard")
    optimizer = OptimizerType[kv.pop("optimizer", "LBFGS").upper()]
    reg_weights = [float(x) for x in kv.pop("reg.weights", "0").split("|")]
    reg_alpha = float(kv.pop("reg.alpha", "0"))
    max_iter = int(kv["max.iter"]) if "max.iter" in kv else None
    kv.pop("max.iter", None)
    tol = float(kv["tol"]) if "tol" in kv else None
    kv.pop("tol", None)
    re_type = kv.pop("random.effect.type", None)
    if re_type is None:
        rate = float(kv["down.sampling.rate"]) if "down.sampling.rate" in kv else None
        kv.pop("down.sampling.rate", None)
        if kv:
            raise ValueError(f"unknown coordinate keys: {sorted(kv)}")
        return FixedEffectCoordinateConfig(
            coordinate_id=name, feature_shard=shard, optimizer=optimizer,
            max_iter=max_iter, tol=tol, reg_weights=reg_weights,
            reg_alpha=reg_alpha, down_sampling_rate=rate,
        )
    ub = int(kv["active.data.upper.bound"]) if "active.data.upper.bound" in kv else None
    kv.pop("active.data.upper.bound", None)
    lb = int(kv["active.data.lower.bound"]) if "active.data.lower.bound" in kv else None
    kv.pop("active.data.lower.bound", None)
    ratio = (
        float(kv["features.to.samples.ratio"])
        if "features.to.samples.ratio" in kv
        else None
    )
    kv.pop("features.to.samples.ratio", None)
    active_set = kv.pop("active.set", "false").strip().lower() in ("1", "true", "yes")
    conv_tol = float(kv["convergence.tol"]) if "convergence.tol" in kv else None
    kv.pop("convergence.tol", None)
    if kv:
        raise ValueError(f"unknown coordinate keys: {sorted(kv)}")
    return RandomEffectCoordinateConfig(
        coordinate_id=name, re_type=re_type, feature_shard=shard,
        optimizer=optimizer, max_iter=max_iter, tol=tol,
        reg_weights=reg_weights, reg_alpha=reg_alpha,
        active_upper_bound=ub, active_lower_bound=lb,
        features_to_samples_ratio=ratio,
        active_set=active_set, convergence_tol=conv_tol,
    )


def add_common_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--input-paths", nargs="+", required=True,
                   help="Avro files/dirs/globs of training data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard-configurations", nargs="+", default=["name=global"],
                   help="name=<shard>,feature.bags=a|b,intercept=true")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType])
    p.add_argument("--input-data-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd over daily-format input dirs "
                        "(reference inputDataDateRange)")
    p.add_argument("--input-data-days-range", default=None,
                   help="start-end days ago (reference inputDataDaysRange)")
    p.add_argument("--override-output-dir", action="store_true")
    p.add_argument(
        "--input-column-names", default=None,
        help="remap reserved columns (reference inputColumnNames / "
             "InputColumnsNames), e.g. "
             "response=the_label,weight=w,offset=off,uid=id,metadata=meta",
    )
    p.add_argument("--verbose", action="store_true")


def parse_input_column_names(spec):
    """'response=the_label,weight=w' → InputColumnsNames (None passthrough)."""
    if not spec:
        return None
    from photon_tpu.io.data_reader import InputColumnsNames

    allowed = {"response", "offset", "weight", "uid", "metadata"}
    kwargs = {}
    for part in spec.split(","):
        key, _, value = part.partition("=")
        key = key.strip()
        if key not in allowed or not value:
            raise ValueError(
                f"bad --input-column-names entry {part!r}; keys: {sorted(allowed)}"
            )
        kwargs[key] = value.strip()
    return InputColumnsNames(**kwargs)


def add_active_set_args(p: argparse.ArgumentParser) -> None:
    """Convergence-gated active-set flags shared by all drivers.

    Only the GAME training driver acts on them (random-effect coordinates);
    the other drivers accept them for CLI-surface parity and warn that they
    are no-ops there.
    """
    p.add_argument(
        "--re-active-set", action="store_true",
        help="after the first CD pass, re-solve only random-effect entities "
             "whose coefficients still move more than --re-convergence-tol; "
             "converged entities keep their coefficients and scores "
             "(one small mask fetch per pass boundary)",
    )
    p.add_argument(
        "--re-convergence-tol", type=float, default=1e-4,
        help="relative coefficient-delta threshold deciding which entities "
             "stay in the active set (default 1e-4)",
    )


def add_out_of_core_args(p: argparse.ArgumentParser) -> None:
    """Out-of-core random-effect residency flags shared by all drivers.

    Only the GAME drivers act on them (random-effect coordinates); the
    fixed-effect-only driver accepts them for CLI-surface parity and warns
    that they are no-ops there.
    """
    p.add_argument(
        "--re-device-budget-mb", type=float, default=None,
        help="device byte budget for random-effect block data + "
             "coefficients; when set, blocks live in a host master "
             "(optionally memory-mapped, see --re-spill-dir) and only a "
             "budgeted working set is device-resident — trains models "
             "bigger than device memory at bit-exact parity",
    )
    p.add_argument(
        "--re-spill-dir", default=None,
        help="directory for the host master's memory-mapped .npy spill "
             "(default: host RAM); only meaningful with "
             "--re-device-budget-mb",
    )
    p.add_argument(
        "--re-spill-member", default=None,
        help="ring-member tag for the host-owned spill layout: spill "
             "files land under <re-spill-dir>/host-<k>/ so a fleet "
             "rebalance is a file move, not a row re-stream (see "
             "re_store.rebalance_spill_layout); only meaningful with "
             "--re-spill-dir",
    )


def add_validation_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=["VALIDATE_FULL", "VALIDATE_SAMPLE", "VALIDATE_DISABLED"],
                   help="row-level sanity checks (reference DataValidators)")


def resolve_input_paths(args) -> list:
    """Expand --input-paths through any date/days range (IOUtils role)."""
    from photon_tpu.utils.io_utils import date_range_from_specs, resolve_range_paths

    date_range = date_range_from_specs(
        getattr(args, "input_data_date_range", None),
        getattr(args, "input_data_days_range", None),
    )
    return resolve_range_paths(args.input_paths, date_range)


def task_of(args) -> TaskType:
    return TaskType[args.task]
