"""photon-tpu-obs: read the serving observability plane from a terminal.

Thin stdlib-only client for the three observability endpoints every
deployment shape serves (in-process, ``--workers N``, fleet front end):

- ``traces``  — ``GET /v1/traces``: the tail-based flight recorder's kept
  span trees (slow / errored / degraded / client-forced requests), merged
  across processes by trace id and printed as indented trees with the pid
  of the process each span ran in. ``--follow`` polls and prints only
  traces it has not shown yet.
- ``metrics`` — ``GET /metrics``: the fleet-merged Prometheus text
  exposition, optionally filtered to a name prefix.
- ``slo``     — ``GET /healthz``: the SLO block (per-objective burn rates
  and ok/warn/page state) plus the telemetry-sink health block.

Deliberately free of photon_tpu imports at module level: ``--help`` and a
scrape against a remote host must work without jax or the model stack.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional


def _get(url: str, timeout_s: float = 30.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:  # noqa: S310
        return resp.read()


def _get_json(url: str, timeout_s: float = 30.0):
    return json.loads(_get(url, timeout_s).decode())


# ---------------------------------------------------------------------------
# traces
# ---------------------------------------------------------------------------


def _span_children(spans: List[dict]) -> Dict[Optional[str], List[dict]]:
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s.get("spanId") for s in spans}
    for s in spans:
        parent = s.get("parentSpanId")
        # A span whose parent was recorded in a process we could not
        # scrape still prints — promoted to a root rather than dropped.
        if parent not in ids:
            parent = None
        by_parent.setdefault(parent, []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("start_s") or 0.0)
    return by_parent

def _format_span(s: dict, depth: int) -> str:
    dur = s.get("duration_s")
    dur_txt = f"{dur * 1000:.2f}ms" if isinstance(dur, (int, float)) else "?"
    return (
        f"  {'  ' * depth}{s.get('name', '?')}  {dur_txt}"
        f"  [pid {s.get('pid', '?')}  span {s.get('spanId', '?')}]"
    )


def format_trace(entry: dict) -> str:
    lat = entry.get("latencySeconds")
    lat_txt = f"{lat * 1000:.2f}ms" if isinstance(lat, (int, float)) else "?"
    head = (
        f"trace {entry.get('traceId', '?')}  reason={entry.get('reason', '?')}"
        f"  latency={lat_txt}  pids={entry.get('pids', [])}"
    )
    if entry.get("error"):
        head += f"  error={entry['error']!r}"
    if entry.get("degraded"):
        head += "  degraded"
    lines = [head]
    spans = entry.get("spans") or []
    by_parent = _span_children(spans)
    seen = set()

    def _walk(parent: Optional[str], depth: int) -> None:
        for s in by_parent.get(parent, []):
            sid = s.get("spanId")
            if sid in seen:
                continue
            seen.add(sid)
            lines.append(_format_span(s, depth))
            if sid is not None:
                _walk(sid, depth + 1)

    _walk(None, 0)
    return "\n".join(lines)


def cmd_traces(args: argparse.Namespace) -> int:
    url = args.url.rstrip("/") + "/v1/traces"
    if args.limit is not None:
        url += "?" + urllib.parse.urlencode({"limit": args.limit})
    wanted = getattr(args, "trace_id", None)
    shown = set()
    while True:
        try:
            payload = _get_json(url)
        except (urllib.error.URLError, OSError) as exc:
            print(f"photon-tpu-obs: {url}: {exc}", file=sys.stderr)
            return 1
        entries = payload.get("traces") or []
        if wanted:
            # Exemplar resolution: a trace_id scraped off a /metrics
            # histogram line jumps straight to its kept span tree.
            # Prefix match, so a truncated id from a dashboard works.
            entries = [
                e for e in entries
                if str(e.get("traceId", "")).startswith(wanted)
            ]
        fresh = [e for e in entries if e.get("traceId") not in shown]
        for e in fresh:
            shown.add(e.get("traceId"))
            if args.json:
                print(json.dumps(e))
            else:
                print(format_trace(e))
                print()
        if not args.follow:
            if not entries:
                if wanted:
                    print(
                        f"photon-tpu-obs: trace {wanted!r} not in the "
                        "flight recorder (evicted, or kept by another "
                        "process?)",
                        file=sys.stderr,
                    )
                    return 1
                print("(no kept traces)")
            return 0
        time.sleep(args.interval)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


# One exposition sample, optionally carrying an OpenMetrics exemplar
# (`name{labels} value # {trace_id="..."} exemplar_value`).
_SAMPLE_RE = re.compile(
    r'^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r'\s+(?P<value>[^\s#]+)'
    r'(?:\s+#\s+\{(?P<exlabels>[^}]*)\}\s+(?P<exvalue>\S+))?\s*$'
)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_labels(blob: Optional[str]) -> Dict[str, str]:
    if not blob:
        return {}
    return {k: v for k, v in _LABEL_RE.findall(blob)}


def parse_prometheus(text: str) -> List[dict]:
    """Parse a Prometheus/OpenMetrics text scrape into sample dicts
    (``{"name", "labels", "value"}`` plus ``"exemplar"`` when the line
    carries one). Comment/HELP/TYPE lines and malformed lines are
    skipped — this is a triage tool, not a validator."""
    samples: List[dict] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        sample = {
            "name": m.group("name"),
            "labels": _parse_labels(m.group("labels")),
            "value": value,
        }
        if m.group("exvalue") is not None:
            try:
                ex_value = float(m.group("exvalue"))
            except ValueError:
                ex_value = None
            sample["exemplar"] = {
                "labels": _parse_labels(m.group("exlabels")),
                "value": ex_value,
            }
        samples.append(sample)
    return samples


def cmd_metrics(args: argparse.Namespace) -> int:
    url = args.url.rstrip("/") + "/metrics"
    try:
        text = _get(url).decode()
    except (urllib.error.URLError, OSError) as exc:
        print(f"photon-tpu-obs: {url}: {exc}", file=sys.stderr)
        return 1
    if getattr(args, "json", False):
        samples = parse_prometheus(text)
        if args.prefix:
            samples = [
                s for s in samples if s["name"].startswith(args.prefix)
            ]
        print(json.dumps({"samples": samples}, indent=2))
        return 0
    for line in text.splitlines():
        if not args.prefix:
            print(line)
            continue
        if line.startswith("#"):
            # Keep a TYPE/HELP header only when its metric matches.
            parts = line.split()
            if len(parts) >= 3 and parts[2].startswith(args.prefix):
                print(line)
        elif line.startswith(args.prefix):
            print(line)
    return 0


# ---------------------------------------------------------------------------
# quality
# ---------------------------------------------------------------------------


def quality_rows(samples: List[dict]) -> List[dict]:
    """Fold ``quality_*`` samples into one row per metric label set
    (model_version, tenant, re_type — plus whatever replica labels the
    fleet merge added). The label-delay summary's quantile label is the
    only one folded INTO a row rather than splitting rows."""
    rows: Dict[tuple, dict] = {}

    def row(labels: Dict[str, str]) -> dict:
        ident = {k: v for k, v in labels.items() if k != "quantile"}
        key = tuple(sorted(ident.items()))
        return rows.setdefault(key, {"labels": ident})

    for s in samples:
        name, labels, value = s["name"], s["labels"], s["value"]
        if name == "quality_auc":
            row(labels)["auc"] = value
        elif name == "quality_ece":
            row(labels)["ece"] = value
        elif name == "quality_auc_lift":
            row(labels)["auc_lift"] = value
        elif name in ("quality_logloss", "quality_deviance"):
            row(labels)[name[len("quality_"):]] = value
        elif name == "quality_label_delay_s":
            q = labels.get("quantile")
            if q == "0.5":
                row(labels)["label_delay_p50_s"] = value
            elif q == "0.95":
                row(labels)["label_delay_p95_s"] = value
        elif name == "quality_label_delay_s_count":
            row(labels)["labels_observed"] = value
    out = [r for r in rows.values() if len(r) > 1]
    out.sort(key=lambda r: sorted(r["labels"].items()))
    return out


def cmd_quality(args: argparse.Namespace) -> int:
    url = args.url.rstrip("/") + "/metrics"
    try:
        text = _get(url).decode()
    except (urllib.error.URLError, OSError) as exc:
        print(f"photon-tpu-obs: {url}: {exc}", file=sys.stderr)
        return 1
    samples = [
        s for s in parse_prometheus(text)
        if s["name"].startswith("quality_")
    ]
    rows = quality_rows(samples)
    if args.json:
        print(json.dumps({"quality": rows}, indent=2))
        return 0
    if not rows:
        print(
            "(no quality_* metrics in the scrape — no labelled feedback "
            "has reached the quality plane yet, or the window has not "
            "met min_events)"
        )
        return 1

    def fmt(v, digits=4):
        return f"{v:.{digits}f}" if isinstance(v, (int, float)) else "–"

    for r in rows:
        labels = r["labels"]
        ident = "  ".join(
            f"{k}={labels[k]}" for k in sorted(labels) if labels[k]
        )
        loss = (
            f"logloss={fmt(r['logloss'])}" if "logloss" in r
            else f"deviance={fmt(r['deviance'])}" if "deviance" in r
            else ""
        )
        print(f"{ident or '(unlabelled)'}")
        print(
            f"  auc={fmt(r.get('auc'))}"
            f"  lift={fmt(r.get('auc_lift'), 4) if 'auc_lift' in r else '–'}"
            f"  ece={fmt(r.get('ece'))}  {loss}"
        )
        observed = r.get("labels_observed")
        if isinstance(observed, float):
            observed = int(observed)
        print(
            f"  label_delay p50={fmt(r.get('label_delay_p50_s'), 3)}s"
            f" p95={fmt(r.get('label_delay_p95_s'), 3)}s"
            f"  observed={observed if observed is not None else '–'}"
        )
    return 0


# ---------------------------------------------------------------------------
# slo
# ---------------------------------------------------------------------------


def _find_block(stats: dict, key: str) -> Optional[dict]:
    """Depth-first search for the named block: the fleet ``/healthz``
    nests engine stats per replica."""
    if not isinstance(stats, dict):
        return None
    if isinstance(stats.get(key), dict):
        return stats[key]
    for v in stats.values():
        found = _find_block(v, key) if isinstance(v, dict) else None
        if found is not None:
            return found
    return None


def cmd_slo(args: argparse.Namespace) -> int:
    url = args.url.rstrip("/") + "/healthz"
    try:
        stats = _get_json(url)
    except (urllib.error.URLError, OSError) as exc:
        print(f"photon-tpu-obs: {url}: {exc}", file=sys.stderr)
        return 1
    slo = _find_block(stats, "slo")
    sink = _find_block(stats, "telemetry_sink")
    exporter = _find_block(stats, "otlp_exporter")
    if args.json:
        print(json.dumps(
            {
                "slo": slo,
                "telemetry_sink": sink,
                "otlp_exporter": exporter,
            },
            indent=2,
        ))
        return 0
    if slo is None:
        print("(no slo block in /healthz)")
        return 1
    print(f"overall state: {slo.get('state', '?')}")
    for name, obj in (slo.get("objectives") or {}).items():
        burns = "  ".join(
            f"{w}={b:.2f}" if isinstance(b, (int, float)) else f"{w}=–"
            for w, b in (obj.get("burn") or {}).items()
        )
        print(
            f"  {name}: state={obj.get('state', '?')}"
            f" target={obj.get('target')}"
            f" events={obj.get('events')}  burn: {burns or '–'}"
        )
    if sink is not None:
        print(
            "telemetry sink: "
            f"bytes_written={sink.get('bytes_written')}"
            f" records_dropped={sink.get('records_dropped')}"
            f" write_failures={sink.get('write_failures')}"
            f" last_write_error={sink.get('last_write_error')!r}"
        )
    if exporter is not None:
        print(
            "otlp exporter: "
            f"endpoint={exporter.get('endpoint')}"
            f" queue={exporter.get('queue_depth')}/{exporter.get('queue_cap')}"
            f" exported_spans={exporter.get('exported_spans')}"
            f" dropped_spans={exporter.get('dropped_spans')}"
            f" consecutive_failures={exporter.get('consecutive_failures')}"
            f" last_error={exporter.get('last_error')!r}"
        )
    return 0


# ---------------------------------------------------------------------------
# experiments
# ---------------------------------------------------------------------------


def _render_experiments(doc: dict) -> int:
    exps = doc.get("experiments") or []
    if not exps:
        print(
            "(no experiment generations — nothing under the publish root "
            "carries an `experiment` manifest tag)"
        )
        return 1
    for exp in exps:
        print(
            f"experiment {exp.get('id')}: rounds={exp.get('rounds')}"
            f" candidates={len(exp.get('candidates') or [])}"
            f" poisoned={len(exp.get('poisoned') or [])}"
        )
        for c in exp.get("candidates") or []:
            obs = c.get("observation")
            obs_s = f"{obs:.6f}" if isinstance(obs, (int, float)) else "–"
            flags = []
            if c.get("poisoned"):
                flags.append(f"POISONED({c.get('poisonReason', '?')})")
            if c.get("winner"):
                flags.append("WINNER")
            print(
                f"  r{c.get('round')} {c.get('paramsKey')}"
                f"  gen={c.get('generation')}"
                f"  obs={obs_s}"
                f"  {' '.join(flags)}".rstrip()
            )
        best = exp.get("best")
        if best:
            print(
                f"  best: {best.get('generation')}"
                f" obs={best.get('observation')}"
            )
    return 0


def cmd_experiments(args: argparse.Namespace) -> int:
    if args.publish_root:
        # Offline rollup straight from the generation manifests — works on
        # the publish root with no server running (the manifests ARE the
        # experiment store).
        from photon_tpu.experiment import experiment_summary

        doc = experiment_summary(args.publish_root)
    else:
        url = args.url.rstrip("/") + "/v1/experiment"
        try:
            doc = _get_json(url)
        except (urllib.error.URLError, OSError) as exc:
            print(f"photon-tpu-obs: {url}: {exc}", file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0
    return _render_experiments(doc)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon-tpu-obs",
        description="Inspect a photon-tpu serving endpoint's traces, "
        "metrics, and SLO state.",
    )
    p.add_argument(
        "--url", default="http://127.0.0.1:8000",
        help="serving endpoint base URL (default %(default)s)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("traces", help="dump kept flight-recorder traces")
    t.add_argument("trace_id", nargs="?", default=None,
                   help="show only this trace id (or unique prefix) — "
                        "paste an exemplar's trace_id from a /metrics "
                        "histogram line; exits 1 when absent")
    t.add_argument("--limit", type=int, default=None,
                   help="newest N traces only")
    t.add_argument("--follow", action="store_true",
                   help="poll and print traces as they are kept")
    t.add_argument("--interval", type=float, default=2.0,
                   help="poll interval for --follow (default %(default)s)")
    t.add_argument("--json", action="store_true",
                   help="one JSON entry per line instead of trees")
    t.set_defaults(fn=cmd_traces)

    m = sub.add_parser("metrics", help="dump the Prometheus text scrape")
    m.add_argument("--prefix", default=None,
                   help="only metrics whose name starts with this")
    m.add_argument("--json", action="store_true",
                   help="parse the exposition (labels, values, exemplars) "
                        "and print one JSON document")
    m.set_defaults(fn=cmd_metrics)

    q = sub.add_parser(
        "quality",
        help="per-version/tenant online model quality (AUC, ECE, lift vs "
             "baseline, label delay) from the fleet-merged /metrics scrape",
    )
    q.add_argument("--json", action="store_true",
                   help="rows as one JSON document")
    q.set_defaults(fn=cmd_quality)

    s = sub.add_parser("slo", help="show SLO burn state from /healthz")
    s.add_argument("--json", action="store_true",
                   help="raw slo + telemetry_sink blocks as JSON")
    s.set_defaults(fn=cmd_slo)

    e = sub.add_parser(
        "experiments",
        help="per-experiment candidate lifecycle rollup (rounds, "
             "observations, poisons, winner) from a live /v1/experiment "
             "endpoint or straight from a publish root's manifests",
    )
    e.add_argument("--publish-root", default=None,
                   help="read generation manifests from this dir instead "
                        "of hitting --url (works with no server running)")
    e.add_argument("--json", action="store_true",
                   help="rollup as one JSON document")
    e.set_defaults(fn=cmd_experiments)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
