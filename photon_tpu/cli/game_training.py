"""GAME training driver.

Parity target: reference ``GameTrainingDriver`` (photon-client
cli/game/training/GameTrainingDriver.scala:54-873): read train/validation
Avro → feature maps → stats/normalization → reg-weight cross-product →
GameEstimator.fit → model selection → save models + index maps.

Usage example (grammar mirrors README.md:293-296):

  python -m photon_tpu.cli.game_training \\
    --input-paths train/ --validation-paths valid/ --output-dir out/ \\
    --feature-shard-configurations name=globalShard \\
    --coordinate-configurations \\
      name=global,feature.shard=globalShard,optimizer=LBFGS,reg.weights=0.1|1|10 \\
      name=perUser,feature.shard=globalShard,random.effect.type=userId,reg.weights=1 \\
    --update-sequence global,perUser --evaluators AUC
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict

import jax.numpy as jnp
import numpy as np

from photon_tpu.cli.common import (
    add_common_args,
    parse_coordinate_config,
    parse_feature_shard_config,
    setup_logging,
    task_of,
)
from photon_tpu.data.normalization import build_normalization_context
from photon_tpu.data.stats import compute_feature_stats
from photon_tpu.data.index_map import IndexMap
from photon_tpu.estimators.game_estimator import GameEstimator
from photon_tpu.evaluation.metrics_map import sanitize_for_json
from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
from photon_tpu.io.data_reader import read_merged
from photon_tpu.io.model_io import (
    load_game_model,
    publish_latest_pointer,
    save_game_model,
)
from photon_tpu.types import NormalizationType
from photon_tpu.utils.timed import Timed


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-training")
    add_common_args(p)
    from photon_tpu.cli.common import add_validation_arg

    add_validation_arg(p)
    from photon_tpu.cli.common import add_active_set_args, add_out_of_core_args

    add_active_set_args(p)
    add_out_of_core_args(p)
    p.add_argument("--validation-paths", nargs="*", default=None)
    p.add_argument("--coordinate-configurations", nargs="+", required=True)
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--evaluators", nargs="*", default=["AUC"])
    p.add_argument("--normalization", default="NONE",
                   choices=[t.name for t in NormalizationType])
    p.add_argument("--model-input-dir", default=None, help="warm-start model dir")
    p.add_argument("--locked-coordinates", default="",
                   help="comma-separated coordinate ids to keep fixed (partial retrain)")
    p.add_argument(
        "--coordinate-constraints",
        default=None,
        help='JSON object: coordinate id → constraint array, e.g. '
             '{"global": [{"name": "f1", "term": "", "lowerBound": 0}]}. '
             "GLMSuite bound semantics, resolved against the coordinate's "
             "feature-shard index map; fixed-effect coordinates only",
    )
    p.add_argument(
        "--output-mode",
        default="BEST",
        choices=["BEST", "ALL", "NONE", "EXPLICIT", "TUNED"],
        help="reference ModelOutputMode: BEST = best model overall, ALL = "
             "every trained model, EXPLICIT = best of the explicit λ grid, "
             "TUNED = best hyperparameter-tuned model, NONE = no model output",
    )
    # Hyperparameter auto-tuning (reference GameTrainingDriver.scala:651-692).
    p.add_argument(
        "--hyper-parameter-tuning",
        default="NONE",
        choices=["NONE", "RANDOM", "BAYESIAN"],
        help="tune regularization hyperparameters after the explicit grid "
             "(RANDOM = Sobol search, BAYESIAN = GP + expected improvement)",
    )
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=10)
    p.add_argument(
        "--hyper-parameter-batch-size", type=int, default=1,
        help="candidates evaluated concurrently per tuning round (>1 uses "
             "the vmapped one-program path when the setup allows it — "
             "TPU-parallel tuning, absent in the reference)",
    )
    p.add_argument(
        "--hyper-parameter-tuner",
        default="ATLAS",
        choices=["DUMMY", "ATLAS"],
        help="tuner implementation (reference HyperparameterTunerFactory)",
    )
    p.add_argument(
        "--variance-computation",
        nargs="?",
        const="SIMPLE",
        default="NONE",
        choices=["NONE", "SIMPLE", "FULL"],
        help="coefficient variances: SIMPLE = inverse diagonal Hessian, "
             "FULL = diagonal of Cholesky-inverted Hessian (reference "
             "DistributedOptimizationProblem.scala:83-103); bare flag = SIMPLE",
    )
    p.add_argument(
        "--model-sparsity-threshold", type=float, default=1e-4,
        help="minimum absolute coefficient value considered nonzero when "
             "persisting a model (reference modelSparsityThreshold, default "
             "VectorUtils.DEFAULT_SPARSITY_THRESHOLD = 1e-4)",
    )
    p.add_argument(
        "--ignore-threshold-for-new-models", action="store_true",
        help="during warm start, entities WITHOUT an existing model bypass "
             "the random-effect active-data lower bound (reference "
             "ignoreThresholdForNewModels; requires --model-input-dir)",
    )
    p.add_argument("--checkpoint-dir", default=None,
                   help="mid-training checkpoint/resume directory (resumes "
                        "automatically when state exists)")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="checkpoint cadence in CD iterations")
    p.add_argument("--checkpoint-keep-last", type=int, default=None,
                   help="keep only the newest K step files per checkpoint "
                        "dir (pruned after each save; also pruned before "
                        "the disk-full retry). Default: keep everything, "
                        "or PHOTON_TPU_CHECKPOINT_KEEP_LAST")
    p.add_argument("--resume", action="store_true",
                   help="resume an interrupted run from --checkpoint-dir: "
                        "requires checkpoint state to exist (auto-resume "
                        "merely uses it when present) and keeps the "
                        "existing --output-dir instead of failing on it")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted paths of event listener callables "
                        "(Driver.scala:99-108 registration role)")
    p.add_argument("--event-listener", action="append", default=[],
                   dest="event_listener",
                   help="register one event listener by path "
                        "('pkg.module:attr'); repeatable")
    p.add_argument("--telemetry-out", default=None,
                   help="write the unified run report (spans + metrics + "
                        "coordinate-descent diagnostics) as schema-stable "
                        "JSONL to this path")
    p.add_argument("--otlp-endpoint", default=None,
                   help="base URL of an OTLP/HTTP collector accepting JSON; "
                        "CD pass spans and the metrics registry export there "
                        "(bounded queue, drop-and-count on outage — export "
                        "never blocks training)")
    p.add_argument("--otlp-metrics-interval", type=float, default=15.0,
                   help="seconds between registry-snapshot exports (0 = "
                        "spans only)")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature summary statistics as "
                        "FeatureSummarizationResultAvro, one file per shard "
                        "(ModelProcessingUtils.writeBasicStatistics role)")
    p.add_argument("--feature-index-dir", default=None,
                   help="directory of index-map-<shard>.json files written "
                        "by the feature-indexing driver; skips the distinct "
                        "scan (reference offHeapIndexMapDir role) and is "
                        "required for --stream-ingest-chunk-rows")
    p.add_argument("--stream-ingest-chunk-rows", type=int, default=0,
                   help="read training/validation data through the chunked "
                        "streaming path (host memory bounded by one chunk; "
                        "chunks assemble on the device) instead of the "
                        "slurping reader; needs --feature-index-dir "
                        "(a stream cannot be distinct-scanned first)")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    from photon_tpu.obs import begin_run, finalize_run_report
    from photon_tpu.utils import resources

    begin_run()  # fresh spans / metrics / phase records for THIS run
    from photon_tpu.obs.export import maybe_install_exporter

    otlp = maybe_install_exporter(
        getattr(args, "otlp_endpoint", None), "photon-tpu-training",
        metrics_interval_s=float(
            getattr(args, "otlp_metrics_interval", 0.0) or 0.0
        ),
    )
    # Host RSS watchdog: inert without a detectable limit (cgroup or
    # PHOTON_TPU_RSS_LIMIT_BYTES); under pressure it tightens pipeline queue
    # depths / replay budgets, and the CD pass boundary fails cleanly at the
    # hard level instead of catching the OOM-killer's SIGKILL.
    resources.start_watchdog()
    task = task_of(args)
    from photon_tpu.utils.events import EventEmitter, setup_event

    emitter = EventEmitter()
    for name in list(args.event_listeners) + list(
        getattr(args, "event_listener", [])
    ):
        emitter.register_by_name(name)
    emitter.emit(
        setup_event(
            driver="game_training",
            task=args.task,
            update_sequence=args.update_sequence,
        )
    )

    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))
    coord_configs = [parse_coordinate_config(s) for s in args.coordinate_configurations]
    update_sequence = [s.strip() for s in args.update_sequence.split(",") if s.strip()]
    by_id = {c.coordinate_id: c for c in coord_configs}
    coord_configs = [by_id[cid] for cid in update_sequence]  # order = sequence

    entity_id_columns = {
        c.re_type: c.re_type
        for c in coord_configs
        if hasattr(c, "re_type")
    }

    from photon_tpu.cli.common import parse_input_column_names, resolve_input_paths
    from photon_tpu.data.validators import DataValidationType, validate_game_batch
    from photon_tpu.utils.io_utils import process_output_dir

    column_names = parse_input_column_names(
        getattr(args, "input_column_names", None)
    )
    if args.resume:
        # Explicit resume: checkpoint state must exist (a typo'd dir must
        # not silently start over), and the half-written output dir of the
        # interrupted run is expected — keep it (override would DELETE it,
        # and the checkpoint dir often lives inside).
        from photon_tpu.utils.checkpoint import latest_step

        if not args.checkpoint_dir:
            raise SystemExit("--resume requires --checkpoint-dir")
        # The estimator checkpoints each sweep config under cfg_<i>/; state
        # in ANY of them (or directly in the dir, for older layouts) counts.
        cfg_dirs = [args.checkpoint_dir] + sorted(
            os.path.join(args.checkpoint_dir, d)
            for d in (os.listdir(args.checkpoint_dir)
                      if os.path.isdir(args.checkpoint_dir) else [])
            if d.startswith("cfg_")
        )
        if all(latest_step(d) is None for d in cfg_dirs):
            raise SystemExit(
                f"--resume: no checkpoint state under {args.checkpoint_dir}"
            )
        os.makedirs(args.output_dir, exist_ok=True)
    else:
        process_output_dir(args.output_dir, args.override_output_dir)

    # Pre-built index maps (feature-indexing driver output; reference
    # offHeapIndexMapDir role). Mandatory for streaming ingest — a stream
    # cannot be distinct-scanned first.
    preloaded_maps = None
    if args.feature_index_dir:
        preloaded_maps = {}
        for shard in shard_configs:
            path = os.path.join(
                args.feature_index_dir, f"index-map-{shard}.json"
            )
            try:
                preloaded_maps[shard] = IndexMap.load(path)
            except OSError as exc:
                raise SystemExit(
                    f"--feature-index-dir: cannot read {path} ({exc}); "
                    "expected index-map-<shard>.json files as written by "
                    "the feature-indexing driver, one per configured "
                    f"feature shard ({sorted(shard_configs)})"
                ) from exc
    chunk_rows = int(getattr(args, "stream_ingest_chunk_rows", 0) or 0)
    if chunk_rows > 0 and preloaded_maps is None:
        raise SystemExit(
            "--stream-ingest-chunk-rows requires --feature-index-dir "
            "(run the feature-indexing driver first)"
        )

    def read(paths, index_maps, entity_indexes, intern_new):
        if chunk_rows > 0:
            # Pipelined ingest (io/pipeline.py): decode → assemble → h2d on
            # worker threads with bounded queues, so each chunk's host work
            # overlaps earlier chunks' device placement; unpadded chunks
            # concatenate into one device-resident batch.
            from photon_tpu.io.data_reader import concat_game_batches
            from photon_tpu.io.pipeline import stream_device_batches

            eidx = entity_indexes if entity_indexes is not None else {}
            try:
                chunks = list(stream_device_batches(
                    paths, shard_configs, index_maps,
                    entity_id_columns=entity_id_columns, entity_indexes=eidx,
                    intern_new_entities=intern_new, chunk_rows=chunk_rows,
                    column_names=column_names,
                    telemetry_label="game-train-ingest",
                ))
            except (RuntimeError, ValueError) as exc:
                # Streaming never silently slurps (the user asked for
                # bounded host memory) — fail with actionable guidance.
                raise SystemExit(
                    f"streaming ingest unavailable for {paths}: {exc}; "
                    "drop --stream-ingest-chunk-rows to use the row-codec "
                    "fallback reader"
                ) from exc
            if not chunks:
                raise SystemExit(
                    f"streaming ingest read zero data blocks from {paths}"
                )
            return concat_game_batches([c.batch for c in chunks]), index_maps, eidx
        return read_merged(
            paths, shard_configs, index_maps=index_maps,
            entity_id_columns=entity_id_columns, entity_indexes=entity_indexes,
            intern_new_entities=intern_new, column_names=column_names,
        )

    with Timed("driver/read-train"):
        batch, index_maps, entity_indexes = read(
            resolve_input_paths(args), preloaded_maps, None, True
        )
    # Row-level sanity checks on train + validation data
    # (GameTrainingDriver.scala:415-432).
    validation_mode = DataValidationType[args.data_validation]
    validate_game_batch(batch, task, validation_mode)
    valid_batch = None
    if args.validation_paths:
        with Timed("driver/read-validation"):
            valid_batch, _, _ = read(
                args.validation_paths, index_maps, entity_indexes, False
            )
        validate_game_batch(valid_batch, task, validation_mode)

    # Feature stats + normalization per shard (GameTrainingDriver.scala:434-440).
    intercept_indices = {
        shard: index_maps[shard].get_index(IndexMap.INTERCEPT)
        for shard in shard_configs
        if index_maps[shard].get_index(IndexMap.INTERCEPT) >= 0
    }
    normalization = {}
    norm_type = NormalizationType[args.normalization]
    if norm_type != NormalizationType.NONE or args.summarization_output_dir:
        for shard in shard_configs:
            stats = compute_feature_stats(
                batch.labeled_batch(shard), intercept_indices.get(shard)
            )
            if norm_type != NormalizationType.NONE:
                normalization[shard] = build_normalization_context(
                    norm_type, stats.mean, stats.std, stats.abs_max,
                    intercept_indices.get(shard),
                )
            if args.summarization_output_dir:
                from photon_tpu.io.model_io import write_basic_statistics

                write_basic_statistics(
                    stats, index_maps[shard],
                    os.path.join(
                        args.summarization_output_dir, shard, "part-00000.avro"
                    ),
                )

    # Per-feature constraint maps → per-coordinate bound vectors
    # (GLMSuite.scala:49-126 semantics, GAME-side extension).
    if args.coordinate_constraints:
        import dataclasses as _dc

        from photon_tpu.data.constraints import constraint_bound_vectors
        from photon_tpu.estimators.config import FixedEffectCoordinateConfig

        cmap = json.loads(args.coordinate_constraints)
        unknown = set(cmap) - {c.coordinate_id for c in coord_configs}
        if unknown:
            raise ValueError(f"constraints for unknown coordinates: {sorted(unknown)}")
        for i, c in enumerate(coord_configs):
            entries = cmap.get(c.coordinate_id)
            if entries is None:
                continue
            if not isinstance(c, FixedEffectCoordinateConfig):
                raise ValueError(
                    f"coordinate constraints apply to fixed-effect coordinates "
                    f"only; '{c.coordinate_id}' is a random-effect coordinate"
                )
            bounds = constraint_bound_vectors(
                json.dumps(entries),
                index_maps[c.feature_shard],
                batch.features[c.feature_shard].shape[1],
                intercept_indices.get(c.feature_shard),
            )
            if bounds is not None:
                coord_configs[i] = _dc.replace(
                    c, box=(jnp.asarray(bounds[0]), jnp.asarray(bounds[1]))
                )

    warm = None
    if args.model_input_dir:
        warm = load_game_model(args.model_input_dir, index_maps, entity_indexes)

    num_entities = {k: len(v) for k, v in entity_indexes.items()}
    suite = EvaluationSuite(
        [EvaluatorSpec.parse(e) for e in args.evaluators], num_entities
    ) if args.evaluators else None

    estimator = GameEstimator(
        task=task,
        coordinate_configs=coord_configs,
        num_iterations=args.coordinate_descent_iterations,
        intercept_indices=intercept_indices,
        normalization=normalization,
        num_entities=num_entities,
        locked_coordinates=[s for s in args.locked_coordinates.split(",") if s],
        variance_computation=args.variance_computation,
        ignore_threshold_for_new_models=args.ignore_threshold_for_new_models,
        warm_start_model=warm,
        re_active_set=args.re_active_set,
        re_convergence_tol=args.re_convergence_tol,
        re_device_budget_mb=args.re_device_budget_mb,
        re_spill_dir=args.re_spill_dir,
        re_spill_member=args.re_spill_member,
    )
    from photon_tpu.utils.events import training_finish_event, training_start_event

    emitter.emit(
        training_start_event(
            task=task.value, coordinates=list(update_sequence)
        )
    )
    from photon_tpu.utils.shutdown import GracefulShutdown, handle_termination

    try:
        with handle_termination():
            results = estimator.fit(
                batch,
                validation_batch=valid_batch,
                evaluation_suite=suite if valid_batch is not None else None,
                initial_model=warm,
                checkpoint_dir=args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every,
                checkpoint_keep_last=args.checkpoint_keep_last,
                emitter=emitter,
            )
    except GracefulShutdown as exc:
        # The CD loop already wrote a final pass-boundary checkpoint;
        # finalize telemetry so the interrupted run still reports, then
        # exit with the conventional killed-by-signal code.
        finalize_run_report(
            "game_training", path=args.telemetry_out, emitter=emitter
        )
        if otlp is not None:
            from photon_tpu.obs.export import uninstall_exporter

            try:
                otlp.export_metrics()
                otlp.flush(timeout_s=3.0)
            except Exception:  # noqa: BLE001
                pass
            uninstall_exporter()
        raise SystemExit(128 + exc.signum) from exc

    # --- hyperparameter auto-tuning (runHyperparameterTuning role,
    # reference GameTrainingDriver.scala:651-692) ---
    tuned_results = []
    if args.hyper_parameter_tuning != "NONE":
        tuned_results = _run_hyperparameter_tuning(
            args, estimator, results, batch, valid_batch, suite
        )

    os.makedirs(args.output_dir, exist_ok=True)
    summary = {"configs": [], "tuned_configs": [], "best": None}

    def _select(candidates):
        if not candidates:
            return None
        if suite is not None and valid_batch is not None:
            return estimator.select_best(candidates, suite)
        return candidates[-1]

    # Model selection across explicit + tuned (selectModels role,
    # GameTrainingDriver.scala:701-766): EXPLICIT/TUNED restrict the pool.
    if args.output_mode == "EXPLICIT":
        best = _select(results)
    elif args.output_mode == "TUNED":
        best = _select(tuned_results)
        if best is None:
            raise ValueError(
                "--output-mode TUNED requires --hyper-parameter-tuning with "
                "at least one successful tuning iteration"
            )
    else:
        best = _select(results + tuned_results)

    for key, pool in (("configs", results), ("tuned_configs", tuned_results)):
        for i, r in enumerate(pool):
            summary[key].append({"config": r.config.describe(), "metrics": r.metrics})
            if args.output_mode == "ALL":
                save_game_model(
                    r.model,
                    os.path.join(args.output_dir, "models", f"{key}-{i}"),
                    index_maps, entity_indexes,
                    sparsity_threshold=args.model_sparsity_threshold,
                )
    if args.output_mode != "NONE":
        save_game_model(
            best.model, os.path.join(args.output_dir, "best"),
            index_maps, entity_indexes,
            sparsity_threshold=args.model_sparsity_threshold,
            extra_metadata={"config": best.config.describe()},
        )
        for shard, imap in index_maps.items():
            imap.save(os.path.join(args.output_dir, f"index-map-{shard}.json"))
        for re_type, eidx in entity_indexes.items():
            eidx.save(os.path.join(args.output_dir, f"entity-index-{re_type}.json"))
        # Artifacts are on disk; NOW flip the fsync'd LATEST pointer so a
        # polling game_serving (--reload-poll-interval) hot-swaps a fully
        # written generation, never a partial one.
        publish_latest_pointer(args.output_dir, "best")
    summary["best"] = {"config": best.config.describe(), "metrics": best.metrics}
    with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
        # Non-finite metrics (e.g. AIC at the n−k−1=0 pole) become null:
        # the bare token Infinity is not RFC-8259 JSON.
        json.dump(sanitize_for_json(summary), f, indent=2)
    emitter.emit(
        training_finish_event(best=None if best is None else best.config.describe())
    )
    finalize_run_report(
        "game_training",
        path=args.telemetry_out,
        emitter=emitter,
        trackers=[
            {
                "label": f"{key}[{i}]",
                "tracker": r.tracker,
                "wall_times": r.wall_times,
            }
            for key, pool in (
                ("config", results), ("tuned", tuned_results)
            )
            for i, r in enumerate(pool)
        ],
    )
    if otlp is not None:
        from photon_tpu.obs.export import uninstall_exporter

        try:
            otlp.export_metrics()
            otlp.flush(timeout_s=3.0)
        except Exception:  # noqa: BLE001 — export is best-effort at exit
            pass
        uninstall_exporter()
    return summary


def _run_hyperparameter_tuning(args, estimator, results, batch, valid_batch, suite):
    """Bayesian/random search over regularization hyperparameters, seeded
    with the explicit grid as prior observations."""
    import logging

    from photon_tpu.estimators.evaluation_function import (
        GameEstimatorEvaluationFunction,
    )
    from photon_tpu.hyperparameter.serialization import observations_to_json
    from photon_tpu.hyperparameter.tuner import TunerName, TuningMode, get_tuner

    logger = logging.getLogger("photon_tpu.driver")
    if valid_batch is None or suite is None:
        raise ValueError(
            "--hyper-parameter-tuning requires --validation-paths and "
            "--evaluators (the tuner optimizes the primary validation metric)"
        )
    base_config = results[0].config
    is_opt_max = suite.primary.better()(1.0, 0.0)
    fn = GameEstimatorEvaluationFunction(
        estimator, base_config, batch, valid_batch, suite, is_opt_max
    )
    if fn.dim == 0:
        logger.warning(
            "hyperparameter tuning requested but no coordinate is "
            "regularized in the base configuration; skipping"
        )
        return []
    tuner = get_tuner(TunerName[args.hyper_parameter_tuner])
    with Timed(f"driver/hyperparameter-tuning[{args.hyper_parameter_tuning}]"):
        _best_x, _best_v, observations = tuner.search(
            args.hyper_parameter_tuning_iter,
            fn.dim,
            TuningMode[args.hyper_parameter_tuning],
            fn,
            search_range=fn.search_range,
            prior_observations=fn.convert_observations(results),
            batch_size=args.hyper_parameter_batch_size,
        )
    if _best_x is not None and not fn.results:
        # The batched fast path evaluates metrics without materializing
        # models; one sequential fit of the winning candidate gives the
        # TUNED output mode a model to save.
        fn(np.asarray(_best_x))
    os.makedirs(args.output_dir, exist_ok=True)
    with open(
        os.path.join(args.output_dir, "hyperparameter-observations.json"), "w"
    ) as f:
        f.write(observations_to_json(observations, fn.names))
    logger.info(
        "hyperparameter tuning: %d candidates evaluated, observations saved",
        len(fn.results),
    )
    return fn.results


def main(argv=None):
    args = build_parser().parse_args(argv)
    summary = run(args)
    print(json.dumps(summary["best"]))


if __name__ == "__main__":
    main()
