"""Incremental GAME retraining driver: one guarded generation per run.

TPU-new driver (the reference's refresh story is a full re-train plus
offline validation between runs — PAPER.md §2.9; this automates that gate
in-band). Against a *publish root* (the output dir of a previous
``game_training`` run: generations + ``LATEST`` + index-map / entity-index
artifacts), one invocation:

1. reads the DELTA data (rows whose data changed since the parent
   generation; new entities intern into the existing entity index),
2. warm-starts from the ``LATEST`` generation and re-trains only the
   changed entities (active-set machinery; unchanged entities keep the
   parent's coefficients verbatim via a row-level merge),
3. writes the new generation + its manifest (per-file sha256 checksums,
   parent generation id, holdout-metric record),
4. runs the validation gate — checksums, coefficient sanity, holdout
   regression bound vs the parent — and flips the fsync'd ``LATEST``
   pointer ONLY on a pass. A refused generation stays on disk with the
   reason in its manifest; ``game_serving --reload-poll-interval`` never
   sees it.

Usage:

  python -m photon_tpu.cli.game_incremental \\
    --publish-root out/ --input-paths delta/ --validation-paths holdout/ \\
    --coordinate-configurations name=global,feature.shard=globalShard \\
      name=perUser,feature.shard=globalShard,random.effect.type=userId \\
    --update-sequence global,perUser --evaluators AUC
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Dict

from photon_tpu.cli.common import (
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_input_column_names,
    setup_logging,
    task_of,
)
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-incremental")
    p.add_argument("--publish-root", required=True,
                   help="a game_training output dir: generations + LATEST "
                        "pointer + index-map-*.json / entity-index-*.json; "
                        "the new generation is written as a subdir here")
    p.add_argument("--input-paths", nargs="+", required=True,
                   help="delta data — rows whose data changed since the "
                        "parent generation")
    p.add_argument("--validation-paths", nargs="*", default=None,
                   help="holdout data for the gate's regression bound")
    p.add_argument("--feature-shard-configurations", nargs="+",
                   default=["name=global"])
    p.add_argument("--coordinate-configurations", nargs="+", required=True)
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType])
    p.add_argument("--evaluators", nargs="*", default=["AUC"])
    p.add_argument("--input-column-names", default=None)
    p.add_argument("--generation", default=None,
                   help="name for the new generation (default: gen-<N+1>)")
    p.add_argument("--locked-coordinates", default="",
                   help="comma-separated coordinate ids to keep fixed")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--metric-tolerance", type=float, default=0.02,
                   help="gate: max holdout-metric regression vs the parent")
    p.add_argument("--norm-drift-bound", type=float, default=10.0,
                   help="gate: max relative L2 coefficient-norm drift per "
                        "coordinate vs the parent")
    p.add_argument("--re-convergence-tol", type=float, default=1e-4)
    from photon_tpu.cli.common import add_out_of_core_args

    add_out_of_core_args(p)
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0,
                   help="0 keeps all coefficients (exact warm-start round "
                        "trips across the incremental chain)")
    p.add_argument("--dead-letter-in", nargs="*", default=[],
                   help="pipeline dead-letter sidecar JSONL files "
                        "(io/pipeline.py) naming chunks dropped by a "
                        "previous run's skip budget; recorded in the "
                        "generation manifest so the skipped rows are "
                        "targeted by this refresh")
    p.add_argument("--no-publish", action="store_true",
                   help="train + manifest but never touch LATEST (dry run)")
    p.add_argument("--telemetry-out", default=None)
    p.add_argument("--verbose", action="store_true")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    from photon_tpu.data.index_map import EntityIndex, IndexMap
    from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
    from photon_tpu.io.data_reader import read_merged
    from photon_tpu.obs import begin_run, finalize_run_report
    from photon_tpu.train.incremental import incremental_update, read_dead_letters

    begin_run()
    task = task_of(args)
    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))
    coord_configs = [
        parse_coordinate_config(s) for s in args.coordinate_configurations
    ]
    update_sequence = [
        s.strip() for s in args.update_sequence.split(",") if s.strip()
    ]
    by_id = {c.coordinate_id: c for c in coord_configs}
    coord_configs = [by_id[cid] for cid in update_sequence]
    entity_id_columns = {
        c.re_type: c.re_type for c in coord_configs if hasattr(c, "re_type")
    }
    column_names = parse_input_column_names(args.input_column_names)

    # Generation-stable artifacts from the publish root: index maps pin the
    # feature space, entity indexes grow append-only as the delta interns
    # new entities — existing slots never move, so the parent model and any
    # running server stay aligned.
    index_maps = {}
    for shard in shard_configs:
        path = os.path.join(args.publish_root, f"index-map-{shard}.json")
        if os.path.exists(path):
            index_maps[shard] = IndexMap.load(path)
    entity_indexes = {}
    for re_type in entity_id_columns:
        path = os.path.join(args.publish_root, f"entity-index-{re_type}.json")
        if os.path.exists(path):
            entity_indexes[re_type] = EntityIndex.load(path)

    batch, index_maps, entity_indexes = read_merged(
        args.input_paths, shard_configs,
        index_maps=index_maps or None,
        entity_id_columns=entity_id_columns,
        entity_indexes=entity_indexes or None,
        intern_new_entities=True,
        column_names=column_names,
    )
    valid_batch = None
    if args.validation_paths:
        valid_batch, _, _ = read_merged(
            args.validation_paths, shard_configs,
            index_maps=index_maps,
            entity_id_columns=entity_id_columns,
            entity_indexes=entity_indexes,
            intern_new_entities=False,
            column_names=column_names,
        )
    suite = None
    if args.evaluators and valid_batch is not None:
        suite = EvaluationSuite(
            [EvaluatorSpec.parse(e) for e in args.evaluators],
            {k: len(v) for k, v in entity_indexes.items()},
        )

    result = incremental_update(
        args.publish_root,
        batch,
        index_maps,
        entity_indexes,
        task,
        coord_configs,
        update_sequence,
        valid_batch=valid_batch,
        evaluation_suite=suite,
        generation=args.generation,
        locked_coordinates=[
            s for s in args.locked_coordinates.split(",") if s
        ],
        num_iterations=args.coordinate_descent_iterations,
        metric_tolerance=args.metric_tolerance,
        norm_drift_bound=args.norm_drift_bound,
        sparsity_threshold=args.model_sparsity_threshold,
        re_convergence_tol=args.re_convergence_tol,
        re_device_budget_mb=args.re_device_budget_mb,
        re_spill_dir=args.re_spill_dir,
        re_spill_member=args.re_spill_member,
        dead_letters=read_dead_letters(args.dead_letter_in),
        publish=not args.no_publish,
    )
    finalize_run_report("game_incremental", path=args.telemetry_out)
    return {
        "generation": result.generation,
        "modelDir": result.model_dir,
        "published": result.published,
        "gateReason": result.gate_reason,
        "parent": result.parent,
        "holdoutMetrics": result.holdout_metrics,
        "changedEntities": result.changed_entities,
    }


def main(argv=None):
    summary = run(build_parser().parse_args(argv))
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
