"""Streaming GAME updater driver: continuous gated micro-generations.

TPU-new driver closing the freshness loop at traffic speed. Where
``game_incremental`` runs ONE guarded generation per invocation from delta
files, this driver runs as a long-lived process against a *publish root*
and the serving side's feedback spool (``game_serving --feedback-spool``):

1. polls the spool for sealed segments of joined (request, label) records,
2. warm-starts from ``LATEST`` and re-trains only the entities those
   records touched (the same incremental machinery — row-level merge,
   active-set solves),
3. publishes each result as a per-entity DELTA layer (base + changed rows;
   ``--no-delta`` forces full generations) through the same validation gate
   and fsync'd ``LATEST`` pointer,
4. repeats on ``--cadence`` until stopped (or ``--max-cycles`` publishes,
   for bounded runs and tests).

The consume cursor lives in the generation manifests themselves
(``stream.consumedThrough``), so a killed and restarted updater never
double-applies a segment — see ``photon_tpu/stream/updater.py``.

Usage:

  python -m photon_tpu.cli.game_streaming \\
    --publish-root out/ --spool-dir out/feedback/ \\
    --coordinate-configurations name=global,feature.shard=globalShard \\
      name=perUser,feature.shard=globalShard,random.effect.type=userId \\
    --update-sequence global,perUser --cadence 5 --lock-coordinates global
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Dict

from photon_tpu.cli.common import (
    parse_coordinate_config,
    setup_logging,
    task_of,
)
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-streaming")
    p.add_argument("--publish-root", required=True,
                   help="a game_training output dir: generations + LATEST "
                        "pointer + index-map-*.json / entity-index-*.json; "
                        "micro-generations are written as subdirs here")
    p.add_argument("--spool-dir", required=True,
                   help="the feedback spool directory game_serving writes "
                        "(sealed segment-*.jsonl files are consumed)")
    p.add_argument("--coordinate-configurations", nargs="+", required=True)
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType])
    p.add_argument("--cadence", type=float, default=5.0,
                   help="seconds between spool polls")
    p.add_argument("--min-records", type=int, default=8,
                   help="skip the solve until at least this many joined "
                        "records are pending (segments accumulate)")
    p.add_argument("--max-segments", type=int, default=64,
                   help="cap on segments folded into one micro-generation")
    p.add_argument("--max-cycles", type=int, default=None,
                   help="stop after this many publishes (default: run until "
                        "signalled)")
    p.add_argument("--lock-coordinates", default="",
                   help="comma-separated coordinate ids to keep fixed "
                        "(typically the fixed effects: micro-batches are "
                        "too small to re-fit the global model)")
    p.add_argument("--no-delta", action="store_true",
                   help="publish full generations instead of delta layers")
    p.add_argument("--full-every", type=int, default=0,
                   help="force every k-th publish to be a full generation, "
                        "bounding delta-chain length (0: never force)")
    p.add_argument("--holdout-fraction", type=float, default=0.0,
                   help="fraction of records held out (deterministically) "
                        "for the gate's regression bound; 0 disables")
    p.add_argument("--evaluators", nargs="*", default=["AUC"])
    p.add_argument("--metric-tolerance", type=float, default=0.02)
    p.add_argument("--norm-drift-bound", type=float, default=10.0)
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--re-convergence-tol", type=float, default=1e-4)
    p.add_argument("--telemetry-out", default=None)
    p.add_argument("--otlp-endpoint", default=None,
                   help="base URL of an OTLP/HTTP collector accepting JSON; "
                        "updater cycle spans and the metrics registry export "
                        "there (bounded queue, drop-and-count on outage)")
    p.add_argument("--otlp-metrics-interval", type=float, default=15.0,
                   help="seconds between registry-snapshot exports (0 = "
                        "spans only)")
    p.add_argument("--verbose", action="store_true")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    from photon_tpu.data.index_map import EntityIndex, IndexMap
    from photon_tpu.obs import begin_run, finalize_run_report
    from photon_tpu.stream.updater import (
        StreamingUpdater,
        StreamingUpdaterConfig,
    )

    begin_run()
    from photon_tpu.obs.export import maybe_install_exporter, uninstall_exporter

    exporter = maybe_install_exporter(
        args.otlp_endpoint, "photon-tpu-streaming",
        metrics_interval_s=float(args.otlp_metrics_interval or 0.0),
    )
    task = task_of(args)
    coord_configs = [
        parse_coordinate_config(s) for s in args.coordinate_configurations
    ]
    update_sequence = [
        s.strip() for s in args.update_sequence.split(",") if s.strip()
    ]
    by_id = {c.coordinate_id: c for c in coord_configs}
    coord_configs = [by_id[cid] for cid in update_sequence]

    # The publish root's artifacts are authoritative — the updater joins a
    # lineage the batch trainer started, it never invents a feature space.
    index_maps = {}
    for fn in os.listdir(args.publish_root):
        if fn.startswith("index-map-") and fn.endswith(".json"):
            shard = fn[len("index-map-"):-len(".json")]
            index_maps[shard] = IndexMap.load(
                os.path.join(args.publish_root, fn)
            )
    entity_indexes = {}
    for fn in os.listdir(args.publish_root):
        if fn.startswith("entity-index-") and fn.endswith(".json"):
            re_type = fn[len("entity-index-"):-len(".json")]
            entity_indexes[re_type] = EntityIndex.load(
                os.path.join(args.publish_root, fn)
            )
    if not index_maps:
        raise SystemExit(
            f"no index-map-*.json under {args.publish_root!r}: the publish "
            "root must come from a game_training run"
        )

    updater = StreamingUpdater(
        StreamingUpdaterConfig(
            publish_root=args.publish_root,
            spool_dir=args.spool_dir,
            task=task,
            coordinate_configs=coord_configs,
            update_sequence=update_sequence,
            cadence_s=args.cadence,
            min_records=args.min_records,
            max_segments_per_cycle=args.max_segments,
            locked_coordinates=[
                s for s in args.lock_coordinates.split(",") if s
            ],
            delta_artifacts=not args.no_delta,
            full_every=args.full_every,
            holdout_fraction=args.holdout_fraction,
            evaluators=list(args.evaluators),
            metric_tolerance=args.metric_tolerance,
            norm_drift_bound=args.norm_drift_bound,
            num_iterations=args.coordinate_descent_iterations,
            re_convergence_tol=args.re_convergence_tol,
        ),
        index_maps,
        entity_indexes,
    )
    try:
        cycles = updater.run_forever(max_cycles=args.max_cycles)
    except KeyboardInterrupt:
        updater.stop()
        cycles = updater.stats()["cycles"]
    finalize_run_report("game_streaming", path=args.telemetry_out)
    if exporter is not None:
        try:
            exporter.export_metrics()
            exporter.flush(timeout_s=3.0)
        except Exception:  # noqa: BLE001 — export is best-effort at exit
            logger.exception("final OTLP export failed")
        uninstall_exporter()
    stats = updater.stats()
    return {
        "cycles": cycles,
        "publishes": stats["publishes"],
        "consumedThrough": stats["consumed_through"],
    }


def main(argv=None):
    summary = run(build_parser().parse_args(argv))
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
