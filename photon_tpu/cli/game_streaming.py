"""Streaming GAME updater driver: continuous gated micro-generations.

TPU-new driver closing the freshness loop at traffic speed. Where
``game_incremental`` runs ONE guarded generation per invocation from delta
files, this driver runs as a long-lived process against a *publish root*
and the serving side's feedback spool (``game_serving --feedback-spool``):

1. polls the spool for sealed segments of joined (request, label) records,
2. warm-starts from ``LATEST`` and re-trains only the entities those
   records touched (the same incremental machinery — row-level merge,
   active-set solves),
3. publishes each result as a per-entity DELTA layer (base + changed rows;
   ``--no-delta`` forces full generations) through the same validation gate
   and fsync'd ``LATEST`` pointer,
4. repeats on ``--cadence`` until stopped (or ``--max-cycles`` publishes,
   for bounded runs and tests).

The consume cursor lives in the generation manifests themselves
(``stream.consumedThrough``), so a killed and restarted updater never
double-applies a segment — see ``photon_tpu/stream/updater.py``.

Usage:

  python -m photon_tpu.cli.game_streaming \\
    --publish-root out/ --spool-dir out/feedback/ \\
    --coordinate-configurations name=global,feature.shard=globalShard \\
      name=perUser,feature.shard=globalShard,random.effect.type=userId \\
    --update-sequence global,perUser --cadence 5 --lock-coordinates global
"""

from __future__ import annotations

import argparse
import copy
import json
import logging
import os
import threading
from typing import Dict

from photon_tpu.cli.common import (
    parse_coordinate_config,
    setup_logging,
    task_of,
)
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-streaming")
    p.add_argument("--publish-root", required=True,
                   help="a game_training output dir: generations + LATEST "
                        "pointer + index-map-*.json / entity-index-*.json; "
                        "micro-generations are written as subdirs here")
    p.add_argument("--spool-dir", required=True,
                   help="the feedback spool directory game_serving writes "
                        "(sealed segment-*.jsonl files are consumed)")
    p.add_argument("--coordinate-configurations", nargs="+", required=True)
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType])
    p.add_argument("--cadence", type=float, default=5.0,
                   help="seconds between spool polls")
    p.add_argument("--min-records", type=int, default=8,
                   help="skip the solve until at least this many joined "
                        "records are pending (segments accumulate)")
    p.add_argument("--max-segments", type=int, default=64,
                   help="cap on segments folded into one micro-generation")
    p.add_argument("--max-cycles", type=int, default=None,
                   help="stop after this many publishes (default: run until "
                        "signalled)")
    p.add_argument("--lock-coordinates", default="",
                   help="comma-separated coordinate ids to keep fixed "
                        "(typically the fixed effects: micro-batches are "
                        "too small to re-fit the global model)")
    p.add_argument("--updater-shards", type=int, default=1,
                   help="total updater shards in the freshness plane: "
                        "records route to shards by entity hash on the "
                        "serving ring, so each shard owns a disjoint entity "
                        "subset and publishes commuting delta layers")
    p.add_argument("--shard-index", type=int, default=None,
                   help="run ONLY this shard worker (one process per shard, "
                        "the fleet layout); default with --updater-shards>1 "
                        "runs every shard as a thread in this process")
    p.add_argument("--route-re-type", default=None,
                   help="random-effect type whose entity id records route "
                        "on (default: route_key's deterministic fallback "
                        "order, same as serving)")
    p.add_argument("--route-spool", action="store_true",
                   help="materialize the shard partition: a router thread "
                        "splits each sealed segment once into per-shard "
                        "sub-spools under <spool-dir>/.shards/ and workers "
                        "consume only their own — aggregate throughput then "
                        "scales with shard count instead of plateauing at "
                        "the read-side routing scan (threads mode only; a "
                        "--shard-index fleet process should point "
                        "--spool-dir at its pre-routed shard dir instead)")
    p.add_argument("--no-delta", action="store_true",
                   help="publish full generations instead of delta layers")
    p.add_argument("--full-every", type=int, default=0,
                   help="force every k-th publish to be a full generation, "
                        "bounding delta-chain length (0: never force)")
    p.add_argument("--holdout-fraction", type=float, default=0.0,
                   help="fraction of records held out (deterministically) "
                        "for the gate's regression bound; 0 disables")
    p.add_argument("--late-replay-cadence", type=float, default=0.0,
                   help="seconds between late-label replay passes: the "
                        "spool sidecar's (evicted, late_label) pairs "
                        "re-join and retrain into a corrective delta "
                        "through the unchanged gate; 0 disables")
    p.add_argument("--late-replay-min-pairs", type=int, default=8,
                   help="skip a replay pass until at least this many fresh "
                        "joined sidecar pairs exist")
    p.add_argument("--fe-retrain", action="store_true",
                   help="actuate stream_fe_retrain_wanted: when the locked "
                        "fixed effect exceeds --fe-max-age, publish a "
                        "cooldown-guarded full generation with the FE "
                        "coordinate unlocked (counts in "
                        "stream_fe_retrains_total)")
    p.add_argument("--fe-max-age", type=float, default=3600.0,
                   help="seconds before the locked FE's age burns the "
                        "fe_age_s objective and raises the retrain trigger")
    p.add_argument("--fe-retrain-cooldown", type=float, default=600.0,
                   help="minimum seconds between FE retrain attempts "
                        "(failed attempts burn the cooldown too)")
    p.add_argument("--evaluators", nargs="*", default=["AUC"])
    p.add_argument("--metric-tolerance", type=float, default=0.02)
    p.add_argument("--norm-drift-bound", type=float, default=10.0)
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--re-convergence-tol", type=float, default=1e-4)
    p.add_argument(
        "--re-device-budget-mb", type=float, default=None,
        help="device byte budget for random-effect block data during "
             "per-cycle fits (out-of-core residency; None = fully "
             "resident)",
    )
    p.add_argument(
        "--re-spill-dir", default=None,
        help="spill root for the out-of-core host master; sharded "
             "updaters spill under host-<shard>/ (host-owned layout) so "
             "a shard-count rebalance is a file move, not a re-stream "
             "(shard_router.rebalance_updater_spill)",
    )
    p.add_argument("--telemetry-out", default=None)
    p.add_argument("--otlp-endpoint", default=None,
                   help="base URL of an OTLP/HTTP collector accepting JSON; "
                        "updater cycle spans and the metrics registry export "
                        "there (bounded queue, drop-and-count on outage)")
    p.add_argument("--otlp-metrics-interval", type=float, default=15.0,
                   help="seconds between registry-snapshot exports (0 = "
                        "spans only)")
    p.add_argument("--verbose", action="store_true")
    return p


def run(args) -> Dict:
    setup_logging(args.verbose)
    from photon_tpu.data.index_map import EntityIndex, IndexMap
    from photon_tpu.obs import begin_run, finalize_run_report
    from photon_tpu.stream.updater import (
        StreamingUpdater,
        StreamingUpdaterConfig,
    )

    begin_run()
    from photon_tpu.obs.export import maybe_install_exporter, uninstall_exporter

    exporter = maybe_install_exporter(
        args.otlp_endpoint, "photon-tpu-streaming",
        metrics_interval_s=float(args.otlp_metrics_interval or 0.0),
    )
    task = task_of(args)
    coord_configs = [
        parse_coordinate_config(s) for s in args.coordinate_configurations
    ]
    update_sequence = [
        s.strip() for s in args.update_sequence.split(",") if s.strip()
    ]
    by_id = {c.coordinate_id: c for c in coord_configs}
    coord_configs = [by_id[cid] for cid in update_sequence]

    # The publish root's artifacts are authoritative — the updater joins a
    # lineage the batch trainer started, it never invents a feature space.
    index_maps = {}
    for fn in os.listdir(args.publish_root):
        if fn.startswith("index-map-") and fn.endswith(".json"):
            shard = fn[len("index-map-"):-len(".json")]
            index_maps[shard] = IndexMap.load(
                os.path.join(args.publish_root, fn)
            )
    entity_indexes = {}
    for fn in os.listdir(args.publish_root):
        if fn.startswith("entity-index-") and fn.endswith(".json"):
            re_type = fn[len("entity-index-"):-len(".json")]
            entity_indexes[re_type] = EntityIndex.load(
                os.path.join(args.publish_root, fn)
            )
    if not index_maps:
        raise SystemExit(
            f"no index-map-*.json under {args.publish_root!r}: the publish "
            "root must come from a game_training run"
        )

    num_shards = max(1, int(args.updater_shards))
    route_spool = bool(getattr(args, "route_spool", False)) and num_shards > 1
    if route_spool and args.shard_index is not None:
        raise SystemExit(
            "--route-spool runs the router in-process (threads mode); a "
            "fleet shard process should point --spool-dir at its "
            "pre-routed <spool-dir>/.shards/shard-<k> directory instead"
        )
    if route_spool and any(c in args.spool_dir for c in "*?["):
        raise SystemExit(
            "--route-spool needs a single literal --spool-dir (the router "
            "splits one raw spool); multi-spool globs use read-side "
            "routing, which needs no router"
        )
    routed_root = os.path.join(args.spool_dir, ".shards")
    if args.shard_index is not None:
        # One process per shard — the fleet layout. Siblings run elsewhere
        # against the same publish root; the flock'd publish tail and the
        # per-shard manifest cursors are the only coordination.
        shard_indexes = [int(args.shard_index)]
    else:
        shard_indexes = list(range(num_shards))

    def make_updater(shard_index: int) -> StreamingUpdater:
        # Each worker gets its OWN artifact copies (the process-per-shard
        # semantics, emulated in threads): interning is then shard-local,
        # and disjoint routing means no entity id is ever interned by two
        # workers — artifacts stay string-keyed and composable.
        imaps = copy.deepcopy(index_maps)
        eidxs = copy.deepcopy(entity_indexes)
        from photon_tpu.stream.shard_router import shard_spool_dir

        spool_dir = (
            shard_spool_dir(routed_root, shard_index)
            if route_spool else args.spool_dir
        )
        return StreamingUpdater(
            StreamingUpdaterConfig(
                publish_root=args.publish_root,
                spool_dir=spool_dir,
                task=task,
                coordinate_configs=coord_configs,
                update_sequence=update_sequence,
                cadence_s=args.cadence,
                min_records=args.min_records,
                max_segments_per_cycle=args.max_segments,
                locked_coordinates=[
                    s for s in args.lock_coordinates.split(",") if s
                ],
                delta_artifacts=not args.no_delta,
                full_every=args.full_every,
                holdout_fraction=args.holdout_fraction,
                evaluators=list(args.evaluators),
                metric_tolerance=args.metric_tolerance,
                norm_drift_bound=args.norm_drift_bound,
                num_iterations=args.coordinate_descent_iterations,
                re_convergence_tol=args.re_convergence_tol,
                re_device_budget_mb=args.re_device_budget_mb,
                re_spill_dir=args.re_spill_dir,
                num_shards=num_shards,
                shard_index=shard_index,
                route_re_type=args.route_re_type,
                pre_routed=route_spool,
                fe_max_age_s=args.fe_max_age,
                fe_retrain=bool(args.fe_retrain),
                fe_retrain_cooldown_s=args.fe_retrain_cooldown,
                late_replay_cadence_s=args.late_replay_cadence,
                late_replay_min_pairs=args.late_replay_min_pairs,
            ),
            imaps if num_shards > 1 else index_maps,
            eidxs if num_shards > 1 else entity_indexes,
        )

    updaters = [make_updater(k) for k in shard_indexes]
    router_stop = threading.Event()
    router_thread = None
    if route_spool:
        from photon_tpu.stream.shard_router import route_segments

        # Route everything already sealed BEFORE workers start (so bounded
        # --max-cycles runs see their traffic), then keep splitting new
        # segments as they seal. Routing is idempotent, so a crash or
        # restart anywhere in this loop is harmless.
        def _route_loop():
            while not router_stop.is_set():
                try:
                    route_segments(
                        args.spool_dir, routed_root, num_shards,
                        route_re_type=args.route_re_type,
                    )
                except Exception:  # noqa: BLE001 — retried next pass
                    logger.exception("spool routing pass failed")
                router_stop.wait(min(float(args.cadence), 1.0))

        route_segments(
            args.spool_dir, routed_root, num_shards,
            route_re_type=args.route_re_type,
        )
        router_thread = threading.Thread(
            target=_route_loop, name="spool-router", daemon=True
        )
        router_thread.start()
    cycles = 0
    try:
        if len(updaters) == 1:
            cycles = updaters[0].run_forever(max_cycles=args.max_cycles)
        else:
            threads = [
                threading.Thread(
                    target=u.run_forever,
                    kwargs={"max_cycles": args.max_cycles},
                    name=f"updater-shard-{u.config.shard_index}",
                    daemon=True,
                )
                for u in updaters
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            cycles = sum(u.stats()["cycles"] for u in updaters)
    except KeyboardInterrupt:
        for u in updaters:
            u.stop()
        cycles = sum(u.stats()["cycles"] for u in updaters)
    finally:
        router_stop.set()
        if router_thread is not None:
            router_thread.join(timeout=5.0)
    finalize_run_report("game_streaming", path=args.telemetry_out)
    if exporter is not None:
        try:
            exporter.export_metrics()
            exporter.flush(timeout_s=3.0)
        except Exception:  # noqa: BLE001 — export is best-effort at exit
            logger.exception("final OTLP export failed")
        uninstall_exporter()
    all_stats = [u.stats() for u in updaters]
    out = {
        "cycles": cycles,
        "publishes": sum(s["publishes"] for s in all_stats),
        "consumedThrough": max(s["consumed_through"] for s in all_stats),
    }
    if num_shards > 1:
        out["shards"] = {
            str(u.config.shard_index): {
                "cycles": s["cycles"],
                "publishes": s["publishes"],
                "consumedThrough": s["consumed_through"],
            }
            for u, s in zip(updaters, all_stats)
        }
    return out


def main(argv=None):
    summary = run(build_parser().parse_args(argv))
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
