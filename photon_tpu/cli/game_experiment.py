"""Continuous online experiment driver: GP tuner over live shadow traffic.

Runs one experiment (photon_tpu/experiment/) against a publish root that a
``game_training`` / ``game_incremental`` chain produced:

1. serves the ``LATEST`` generation over HTTP (same front end as
   ``game_serving``) with the feedback spool attached — live traffic plus
   label joins are the experiment's measurement substrate;
2. each GP round proposes ``--candidates-per-round`` regularization
   points, trains each as a warm-started candidate generation on the
   delta data (``--input-paths``), and loads them ALL as concurrent
   shadow lanes;
3. observations come from the online quality plane (per-candidate
   streaming AUC / loss over joined labels); candidates that burn against
   the primary are poisoned, the final winner promotes through the
   generation-manifest gate.

Crash-resume: re-running with the same ``--experiment-id`` and
``--seed`` re-proposes every round deterministically and skips whatever
the generation manifests already record — completed candidates are never
re-trained. ``--train-only`` does the training half with no serving
engine at all (the state-rebuild path a supervisor uses after a crash).

Usage:

  photon-tpu-game-experiment \\
    --publish-root out/ --input-paths delta/ --validation-paths holdout/ \\
    --coordinate-configurations name=global,feature.shard=globalShard \\
      name=perUser,feature.shard=globalShard,random.effect.type=userId \\
    --update-sequence global,perUser --evaluators AUC \\
    --experiment-id exp1 --rounds 3 --candidates-per-round 4 \\
    --feedback-spool /tmp/spool --port 8088
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading
from typing import Dict

from photon_tpu.cli.common import (
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_input_column_names,
    setup_logging,
    task_of,
)
from photon_tpu.types import TaskType

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-experiment")
    p.add_argument("--publish-root", required=True,
                   help="a game_training output dir: generations + LATEST "
                        "pointer + index/entity artifacts; candidate "
                        "generations are written as subdirs here")
    p.add_argument("--input-paths", nargs="+", required=True,
                   help="delta data each candidate trains on (warm-started "
                        "from LATEST)")
    p.add_argument("--validation-paths", nargs="*", default=None,
                   help="holdout data for the winner's gate metrics")
    p.add_argument("--feature-shard-configurations", nargs="+",
                   default=["name=global"])
    p.add_argument("--coordinate-configurations", nargs="+", required=True)
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate ids")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType])
    p.add_argument("--evaluators", nargs="*", default=["AUC"])
    p.add_argument("--input-column-names", default=None)
    p.add_argument("--locked-coordinates", default="")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    # -- experiment plane ---------------------------------------------------
    p.add_argument("--experiment-id", required=True,
                   help="stable id; resuming with the same id + seed "
                        "skips already-recorded candidates")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--candidates-per-round", type=int, default=4)
    p.add_argument("--seed", type=int, default=7,
                   help="GP/Sobol seed — resume REQUIRES the original seed "
                        "(proposals must replay identically)")
    p.add_argument("--objective", default="loss", choices=["loss", "auc"],
                   help="online observation the GP minimizes: windowed "
                        "mean loss, or 1 - windowed AUC")
    p.add_argument("--shadow-fraction", type=float, default=0.5,
                   help="per-candidate fraction of primary traffic "
                        "mirrored for divergence accounting")
    p.add_argument("--min-events", type=int, default=None,
                   help="labeled events per candidate before its quality "
                        "reading counts (default: quality plane's bar)")
    p.add_argument("--observe-timeout", type=float, default=120.0)
    p.add_argument("--observe-poll", type=float, default=0.25)
    p.add_argument("--auc-drop-bound", type=float, default=None,
                   help="quality-burn poison bar (default: the quality "
                        "plane's auc_drop_bound)")
    p.add_argument("--loss-burn-ratio", type=float, default=0.5)
    p.add_argument("--burn-checks", type=int, default=2)
    p.add_argument("--no-promote", action="store_true",
                   help="never gate/promote the winner (measure only)")
    p.add_argument("--train-only", action="store_true",
                   help="train missing candidates for rounds whose "
                        "observations are already durable; no engine, no "
                        "serving — the crash-resume worker mode")
    p.add_argument("--metric-tolerance", type=float, default=0.02)
    p.add_argument("--norm-drift-bound", type=float, default=10.0)
    # -- embedded serving (online mode) -------------------------------------
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8088)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--max-delay-ms", type=float, default=2.0)
    p.add_argument("--hot-bytes-mb", type=float, default=64.0)
    p.add_argument("--max-model-versions", type=int, default=0,
                   help="resident-generation cap; 0 = candidates-per-round "
                        "+ 3 (primary, rollback parent, slack)")
    p.add_argument("--shadow-quality-fraction", type=float, default=1.0,
                   help="fraction of joined labels re-scored on each "
                        "candidate's quality lane")
    p.add_argument("--feedback-spool", default=None,
                   help="spool dir for the label join (REQUIRED unless "
                        "--train-only: observations come from it)")
    p.add_argument("--feedback-sample-fraction", type=float, default=1.0)
    p.add_argument("--feedback-segment-records", type=int, default=512)
    p.add_argument("--feedback-segment-age", type=float, default=5.0)
    p.add_argument("--feedback-join-ttl", type=float, default=600.0)
    p.add_argument("--telemetry-out", default=None)
    p.add_argument("--verbose", action="store_true")
    return p


def _read_data(args):
    """Delta + holdout batches against the publish root's pinned feature
    space (same artifact discipline as game_incremental: index maps pin
    slots, entity indexes grow append-only)."""
    from photon_tpu.data.index_map import EntityIndex, IndexMap
    from photon_tpu.evaluation.suite import EvaluationSuite, EvaluatorSpec
    from photon_tpu.io.data_reader import read_merged

    shard_configs: Dict = {}
    for spec in args.feature_shard_configurations:
        shard_configs.update(parse_feature_shard_config(spec))
    coord_configs = [
        parse_coordinate_config(s) for s in args.coordinate_configurations
    ]
    update_sequence = [
        s.strip() for s in args.update_sequence.split(",") if s.strip()
    ]
    by_id = {c.coordinate_id: c for c in coord_configs}
    coord_configs = [by_id[cid] for cid in update_sequence]
    entity_id_columns = {
        c.re_type: c.re_type for c in coord_configs if hasattr(c, "re_type")
    }
    column_names = parse_input_column_names(args.input_column_names)

    index_maps = {}
    for shard in shard_configs:
        path = os.path.join(args.publish_root, f"index-map-{shard}.json")
        if os.path.exists(path):
            index_maps[shard] = IndexMap.load(path)
    entity_indexes = {}
    for re_type in entity_id_columns:
        path = os.path.join(
            args.publish_root, f"entity-index-{re_type}.json"
        )
        if os.path.exists(path):
            entity_indexes[re_type] = EntityIndex.load(path)

    batch, index_maps, entity_indexes = read_merged(
        args.input_paths, shard_configs,
        index_maps=index_maps or None,
        entity_id_columns=entity_id_columns,
        entity_indexes=entity_indexes or None,
        intern_new_entities=True,
        column_names=column_names,
    )
    valid_batch = None
    if args.validation_paths:
        valid_batch, _, _ = read_merged(
            args.validation_paths, shard_configs,
            index_maps=index_maps,
            entity_id_columns=entity_id_columns,
            entity_indexes=entity_indexes,
            intern_new_entities=False,
            column_names=column_names,
        )
    suite = None
    if args.evaluators and valid_batch is not None:
        suite = EvaluationSuite(
            [EvaluatorSpec.parse(e) for e in args.evaluators],
            {k: len(v) for k, v in entity_indexes.items()},
        )
    return (batch, valid_batch, suite, index_maps, entity_indexes,
            coord_configs, update_sequence)


def _build_manager(args, engine=None):
    from photon_tpu.estimators.config import (
        GameOptimizationConfig,
        RegularizationConfig,
    )
    from photon_tpu.experiment import (
        ExperimentConfig,
        ExperimentManager,
        ExperimentSpace,
        IncrementalCandidateTrainer,
    )

    (batch, valid_batch, suite, index_maps, entity_indexes,
     coord_configs, update_sequence) = _read_data(args)
    # Coordinates with a positive configured weight become tunable slots
    # (ExperimentSpace's rule); a 0-weight coordinate stays untuned.
    base = GameOptimizationConfig({
        c.coordinate_id: RegularizationConfig(
            weight=max(c.reg_weights), alpha=c.reg_alpha
        )
        for c in coord_configs
    })
    space = ExperimentSpace(base)
    trainer = IncrementalCandidateTrainer(
        args.publish_root, batch, index_maps, entity_indexes,
        task_of(args), coord_configs, update_sequence,
        valid_batch=valid_batch, evaluation_suite=suite,
        num_iterations=args.coordinate_descent_iterations,
        locked_coordinates=[
            s for s in args.locked_coordinates.split(",") if s
        ],
    )
    cfg = ExperimentConfig(
        experiment_id=args.experiment_id,
        publish_root=args.publish_root,
        rounds=args.rounds,
        candidates_per_round=args.candidates_per_round,
        seed=args.seed,
        shadow_fraction=args.shadow_fraction,
        min_events=args.min_events,
        observe_timeout_s=args.observe_timeout,
        observe_poll_s=args.observe_poll,
        objective=args.objective,
        auc_drop_bound=args.auc_drop_bound,
        loss_burn_ratio=args.loss_burn_ratio,
        burn_checks=args.burn_checks,
        promote_winner=not args.no_promote,
        metric_tolerance=args.metric_tolerance,
        norm_drift_bound=args.norm_drift_bound,
    )
    return ExperimentManager(cfg, space, trainer, engine=engine)


def run(args) -> dict:
    setup_logging(args.verbose)
    from photon_tpu.obs import begin_run, finalize_run_report

    begin_run()
    if args.train_only:
        manager = _build_manager(args, engine=None)
        summary = manager.run(train_only=True)
        finalize_run_report("game_experiment", path=args.telemetry_out)
        return summary

    if not args.feedback_spool:
        raise SystemExit(
            "--feedback-spool is required for online experiments: the "
            "label join is where observations come from (use --train-only "
            "for the engine-less resume mode)"
        )

    from http.server import ThreadingHTTPServer

    from photon_tpu.cli.game_serving import make_handler, resolve_model_dir
    from photon_tpu.serve import ServeConfig, load_engine
    from photon_tpu.stream.spool import FeedbackSpool, SpoolConfig

    max_versions = args.max_model_versions or (args.candidates_per_round + 3)
    config = ServeConfig(
        max_batch_size=args.max_batch_size,
        max_delay_ms=args.max_delay_ms,
        hot_bytes=int(args.hot_bytes_mb * (1 << 20)),
        max_versions=max_versions,
        shadow_fraction=args.shadow_fraction,
        shadow_quality_fraction=args.shadow_quality_fraction,
    )
    model_dir = resolve_model_dir(args.publish_root)
    if model_dir == args.publish_root:
        raise SystemExit(
            f"no LATEST generation under {args.publish_root!r}: the "
            "experiment warm-starts candidates from a published parent"
        )
    engine = load_engine(
        model_dir, artifacts_dir=args.publish_root, config=config
    )
    spool = FeedbackSpool(args.feedback_spool, SpoolConfig(
        segment_max_records=args.feedback_segment_records,
        segment_max_age_s=args.feedback_segment_age,
        sample_fraction=args.feedback_sample_fraction,
        join_ttl_s=args.feedback_join_ttl,
    ))
    spool.start_auto_flush()
    engine.attach_feedback(spool)

    server = ThreadingHTTPServer(
        (args.host, args.port), make_handler(engine)
    )
    server.daemon_threads = True
    server_thread = threading.Thread(
        target=server.serve_forever, kwargs=dict(poll_interval=0.2),
        name="experiment-frontend", daemon=True,
    )
    server_thread.start()
    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    print(json.dumps({
        "experiment": args.experiment_id,
        "serving": True,
        "host": server.server_address[0],
        "port": server.server_address[1],
        "modelVersion": engine.model_version,
    }), flush=True)
    try:
        manager = _build_manager(args, engine=engine)
        summary = manager.run()
    finally:
        server.shutdown()
        server.server_close()
        engine.close(drain=True)
        finalize_run_report("game_experiment", path=args.telemetry_out)
    return summary


def main(argv=None):
    summary = run(build_parser().parse_args(argv))
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
