"""GAME online-serving driver: stdlib HTTP/JSONL front end over the
in-process ServingEngine.

TPU-new driver (no reference counterpart — photon-client ends at batch
scoring): stands up serve/engine.py behind a threaded stdlib HTTP server.
One OS thread per connection feeds the shared micro-batcher, which is
exactly the concurrency shape the batcher was built for: many producer
threads, one flusher, one jitted scorer.

Endpoints (JSON unless noted):

- ``POST /v1/score`` — one request: ``{"features": {shard: [f0..fd] |
  {key: value}}, "entityIds": {reType: id}, "offset": 0.0}`` →
  ``{"score": s, "modelVersion": v}``. 429 on shed, 504 on deadline.
- ``POST /v1/score-batch`` — JSONL body, one request per line → JSONL
  response, one ``{"score": s}`` (or ``{"error": ...}``) per line, order
  preserved.
- ``POST /v1/reload`` — ``{"modelDir": path}``: zero-downtime swap; old
  model serves until the new one is warmed.
- ``GET /healthz`` — engine stats (queue depth, store residency, trace
  counts, model version).

Shutdown (SIGTERM/SIGINT) drains the queue and, with ``--telemetry-out``,
writes the unified run report.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from photon_tpu.cli.common import setup_logging
from photon_tpu.serve.batcher import BackpressureError, DeadlineExceededError
from photon_tpu.serve.engine import ServeConfig, ScoreRequest, load_engine

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-serving")
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--model-artifacts-dir", default=None,
                   help="dir holding index-map-*.json / entity-index-*.json "
                        "(defaults to the parent of the model dir)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8712,
                   help="0 picks an ephemeral port (printed on startup)")
    p.add_argument("--max-batch-size", type=int, default=64,
                   help="micro-batch row cap; rounded UP onto the bucket_dim "
                        "shape grid so warm-up covers every dispatch shape")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="max time the oldest queued request waits for the "
                        "batch to fill before flushing anyway")
    p.add_argument("--queue-cap", type=int, default=1024,
                   help="admission bound: submits beyond this depth are shed "
                        "with HTTP 429 (serve_requests_shed_total)")
    p.add_argument("--hot-bytes-mb", type=float, default=64.0,
                   help="device-byte budget for cached random-effect tables "
                        "(hot store; LRU demotion beyond it)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline (queue wait + scoring); "
                        "expired requests fail 504 without scorer time")
    p.add_argument("--telemetry-out", default=None,
                   help="write the unified run report JSONL here on shutdown")
    p.add_argument("--reload-poll-interval", type=float, default=0.0,
                   help="seconds between checks of the model dir for a new "
                        "generation (a LATEST pointer file naming a subdir, "
                        "or a rewritten model-metadata.json); a change "
                        "triggers a zero-downtime reload. 0 disables — "
                        "reloads then happen only via POST /v1/reload")
    p.add_argument("--verbose", action="store_true")
    return p


def resolve_model_dir(model_dir: str) -> str:
    """Follow a ``LATEST`` pointer file when present: its content names the
    current generation (a subdirectory of ``model_dir``, or an absolute
    path). Without one, ``model_dir`` itself is the generation — its
    metadata mtime is the change signal."""
    p = os.path.join(model_dir, "LATEST")
    if os.path.isfile(p):
        try:
            with open(p) as f:
                name = f.read().strip()
        except OSError:
            return model_dir
        if name:
            cand = name if os.path.isabs(name) else os.path.join(model_dir, name)
            if os.path.isdir(cand):
                return cand
    return model_dir


def _model_fingerprint(directory: str):
    from photon_tpu.io.model_io import METADATA_FILE

    try:
        mtime = os.path.getmtime(os.path.join(directory, METADATA_FILE))
    except OSError:
        mtime = None
    return (directory, mtime)


def _reload_watcher(engine, model_dir: str, interval: float,
                    stop: threading.Event) -> None:
    """Poll ``model_dir`` for a new generation and hot-swap it in. A failed
    reload keeps the current model serving (engine guarantee) and is NOT
    retried until the fingerprint changes again — one attempt per published
    generation, no hot-loop on a broken publish."""
    from photon_tpu.io.model_io import load_game_model

    current = _model_fingerprint(resolve_model_dir(model_dir))
    while not stop.wait(interval):
        target = resolve_model_dir(model_dir)
        fp = _model_fingerprint(target)
        if fp == current:
            continue
        try:
            logger.info("model change detected: reloading from %s", target)
            model = load_game_model(
                target, engine._index_maps, engine._entity_indexes,
                to_device=False,
            )
            engine.reload(model, model_version=target)
        except Exception as exc:  # noqa: BLE001 — old model keeps serving
            logger.warning(
                "auto-reload from %s failed (%s); model %r keeps serving",
                target, exc, engine.model_version,
            )
        current = fp


def _request_from_json(obj: dict) -> ScoreRequest:
    if not isinstance(obj, dict) or "features" not in obj:
        raise ValueError("request must be a JSON object with 'features'")
    return ScoreRequest(
        features=dict(obj["features"]),
        entity_ids=dict(obj.get("entityIds", {})),
        offset=float(obj.get("offset", 0.0)),
        uid=obj.get("uid"),
    )


def make_handler(engine, artifacts_dir):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through logging, not stderr
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, payload: bytes, ctype="application/json"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _reply_json(self, code: int, obj) -> None:
            self._reply(code, (json.dumps(obj) + "\n").encode())

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply_json(200, engine.stats())
            else:
                self._reply_json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            try:
                if self.path == "/v1/score":
                    self._score_one()
                elif self.path == "/v1/score-batch":
                    self._score_jsonl()
                elif self.path == "/v1/reload":
                    self._reload()
                else:
                    self._reply_json(404, {"error": f"no route {self.path}"})
            except BackpressureError as exc:
                self._reply_json(429, {"error": str(exc)})
            except DeadlineExceededError as exc:
                self._reply_json(504, {"error": str(exc)})
            except (ValueError, KeyError, json.JSONDecodeError) as exc:
                self._reply_json(400, {"error": str(exc)})
            except Exception as exc:  # noqa: BLE001 — 500, keep serving
                logger.exception("request failed")
                self._reply_json(500, {"error": str(exc)})

        def _score_one(self):
            req = _request_from_json(json.loads(self._body()))
            score = engine.submit(req).result()
            self._reply_json(
                200, {"score": score, "modelVersion": engine.model_version}
            )

        def _score_jsonl(self):
            # Submit every line first (they co-batch), then collect in
            # order — a serial submit/await loop would defeat micro-batching.
            futures = []
            for line in self._body().splitlines():
                if not line.strip():
                    continue
                try:
                    futures.append(
                        engine.submit(_request_from_json(json.loads(line)))
                    )
                except (BackpressureError, ValueError,
                        json.JSONDecodeError) as exc:
                    futures.append(exc)
            out = []
            for f in futures:
                if isinstance(f, Exception):
                    out.append({"error": str(f)})
                else:
                    try:
                        out.append({"score": f.result()})
                    except Exception as exc:  # noqa: BLE001 — per-line error
                        out.append({"error": str(exc)})
            payload = "".join(json.dumps(o) + "\n" for o in out).encode()
            self._reply(200, payload, ctype="application/jsonl")

        def _reload(self):
            from photon_tpu.io.model_io import load_game_model

            body = json.loads(self._body()) if self.headers.get(
                "Content-Length"
            ) else {}
            model_dir = body.get("modelDir")
            if not model_dir:
                raise ValueError("reload needs {'modelDir': path}")
            # Index maps / entity indexes are generation-stable artifacts
            # (the training pipeline reuses them across model refreshes);
            # only the coefficient tables swap.
            model = load_game_model(
                model_dir, engine._index_maps, engine._entity_indexes,
                to_device=False,
            )
            info = engine.reload(model, body.get("modelVersion") or model_dir)
            self._reply_json(200, info)

    return Handler


def run(args):
    setup_logging(args.verbose)
    from photon_tpu.obs import begin_run, finalize_run_report

    begin_run()
    config = ServeConfig(
        max_batch_size=args.max_batch_size,
        max_delay_ms=args.max_delay_ms,
        queue_cap=args.queue_cap,
        hot_bytes=int(args.hot_bytes_mb * (1 << 20)),
        default_deadline_ms=args.deadline_ms,
    )
    logger.info("loading + warming model from %s", args.model_input_dir)
    engine = load_engine(
        args.model_input_dir,
        artifacts_dir=args.model_artifacts_dir,
        config=config,
    )
    server = ThreadingHTTPServer(
        (args.host, args.port), make_handler(engine, args.model_artifacts_dir)
    )
    server.daemon_threads = True
    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    if args.reload_poll_interval and args.reload_poll_interval > 0:
        threading.Thread(
            target=_reload_watcher,
            args=(engine, args.model_input_dir, args.reload_poll_interval, stop),
            name="model-reload-watcher",
            daemon=True,
        ).start()
    print(json.dumps({
        "serving": True,
        "host": server.server_address[0],
        "port": server.server_address[1],
        "maxBatchSize": engine.max_batch,
        "modelVersion": engine.model_version,
    }), flush=True)
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        engine.close(drain=True)
        server.server_close()
        finalize_run_report("game_serving", path=args.telemetry_out)
        print(json.dumps({"serving": False, "stats": engine.stats()}))


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
