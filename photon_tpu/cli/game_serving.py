"""GAME online-serving driver: HTTP/JSONL front end over the ServingEngine.

TPU-new driver (no reference counterpart — photon-client ends at batch
scoring). Two deployment shapes share ONE endpoint implementation
(serve/frontend.py):

- ``--workers 0`` (default): the original in-process shape — a threaded
  stdlib HTTP server feeding the engine directly. Right for tests, smoke
  stages, and single-tenant batch backfill.
- ``--workers N``: the traffic shape — N forked HTTP worker processes
  accept/parse on a shared listening socket and relay over a Unix-domain
  socket to THIS process, which owns the device and runs the same
  admission → MicroBatcher → ServingEngine path. Request parsing no longer
  shares a GIL with the scorer; bit-parity and the zero-retrace contract
  are unchanged because the scoring path is byte-for-byte the same.

Endpoints (JSON unless noted):

- ``POST /v1/score`` — one request: ``{"features": {shard: [f0..fd] |
  {key: value}}, "entityIds": {reType: id}, "offset": 0.0}`` →
  ``{"score": s, "modelVersion": v}``. 429 on shed (quota or
  backpressure — ``kind`` in the body tells which), 504 on deadline.
- ``POST /v1/score-batch`` — JSONL body, one request per line → JSONL
  response, one ``{"score": s}`` (or per-line ``{"error", "code",
  "kind"}``) per line, order preserved. A malformed line is a per-line
  400; it never masquerades as a 429 shed.
- ``POST /v1/reload`` — ``{"modelDir": path}``: zero-downtime swap; old
  model serves until the new one is warmed.
- ``GET /healthz`` — engine stats (queue depth, store residency, trace
  counts, model version, per-tenant admission state).

Multi-tenant admission: ``X-Tenant`` / ``X-Priority`` headers (or
``tenant``/``priority`` request fields) route each request through
token-bucket QPS quotas (``--tenant-qps a=50,b=500``) and priority classes
(interactive vs batch) — see serve/admission.py.

Shutdown (SIGTERM/SIGINT) drains workers first, then the queue, and with
``--telemetry-out`` writes the unified run report (size-capped via
``--telemetry-max-mb``, flushed periodically under
``--telemetry-flush-interval`` so soaks are observable live).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import signal
import threading
from http.server import ThreadingHTTPServer
from typing import Optional

from photon_tpu.cli.common import setup_logging
from photon_tpu.serve.admission import AdmissionConfig, parse_tenant_rates
from photon_tpu.serve.batcher import BackpressureError, DeadlineExceededError
from photon_tpu.serve.engine import ServeConfig, ScoreRequest, load_engine
from photon_tpu.serve.frontend import (
    LocalBackend,
    ServingFrontend,
    make_http_handler,
    request_from_json,
)

__all__ = [
    "BackpressureError",
    "DeadlineExceededError",
    "ScoreRequest",
    "build_parser",
    "main",
    "make_handler",
    "resolve_model_dir",
    "run",
]

logger = logging.getLogger(__name__)

_request_from_json = request_from_json  # back-compat alias


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("game-serving")
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--model-artifacts-dir", default=None,
                   help="dir holding index-map-*.json / entity-index-*.json "
                        "(defaults to the parent of the model dir)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8712,
                   help="0 picks an ephemeral port (printed on startup)")
    p.add_argument("--workers", type=int, default=0,
                   help="HTTP worker processes. 0 = in-process threaded "
                        "server (tests/smoke). N>0 forks N parse/accept "
                        "workers sharing one listen socket, relaying over a "
                        "Unix socket to this device-owning scorer process")
    p.add_argument("--scorer-endpoint", default=None,
                   help="override the worker->scorer relay endpoint: a "
                        "filesystem path (Unix socket, the default: a "
                        "tempdir socket) or tcp://host:port for a "
                        "cross-host scorer. TCP needs an explicit port "
                        "(workers fork before the scorer binds) and the "
                        "shared secret in $PHOTON_TPU_FLEET_SECRET — "
                        "never on argv")
    p.add_argument("--max-batch-size", type=int, default=64,
                   help="micro-batch row cap; rounded UP onto the bucket_dim "
                        "shape grid so warm-up covers every dispatch shape")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="max time the oldest queued request waits for the "
                        "batch to fill before flushing anyway")
    p.add_argument("--queue-cap", type=int, default=1024,
                   help="admission bound: submits beyond this depth are shed "
                        "with HTTP 429 (serve_requests_shed_total)")
    p.add_argument("--hot-bytes-mb", type=float, default=64.0,
                   help="device-byte budget for cached random-effect tables "
                        "(hot store; LRU demotion beyond it)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline (queue wait + scoring); "
                        "expired requests fail 504 without scorer time")
    p.add_argument("--tenant-default-qps", type=float, default=None,
                   help="token-bucket QPS quota for tenants not named in "
                        "--tenant-qps (unset = unknown tenants are "
                        "quota-exempt)")
    p.add_argument("--tenant-default-burst", type=float, default=None,
                   help="bucket burst capacity for the default quota")
    p.add_argument("--tenant-qps", default=None,
                   help="per-tenant QPS quotas, e.g. 'abuser=50,partner=500'")
    p.add_argument("--tenant-burst", default=None,
                   help="per-tenant burst capacities, same syntax")
    p.add_argument("--batch-queue-fraction", type=float, default=0.5,
                   help="batch-priority requests are admitted only while "
                        "queue depth is below this fraction of --queue-cap "
                        "(the rest is reserved for interactive traffic)")
    p.add_argument("--telemetry-out", default=None,
                   help="write the unified run report JSONL here on shutdown")
    p.add_argument("--telemetry-flush-interval", type=float, default=0.0,
                   help="seconds between live run-report rewrites during "
                        "serving (0 = only at shutdown)")
    p.add_argument("--telemetry-max-mb", type=float, default=64.0,
                   help="byte budget for the run report: the previous file "
                        "rotates to <path>.1 and span records drop "
                        "oldest-first to fit (0 = unbounded)")
    p.add_argument("--reload-poll-interval", type=float, default=0.0,
                   help="seconds between checks of the model dir for a new "
                        "generation (a LATEST pointer file naming a subdir, "
                        "or a rewritten model-metadata.json); a change "
                        "triggers a zero-downtime reload. 0 disables — "
                        "reloads then happen only via POST /v1/reload")
    p.add_argument("--shadow-fraction", type=float, default=0.0,
                   help="fraction of live primary traffic re-scored on a "
                        "newly detected generation BEFORE it can become "
                        "primary (divergence recorded, responses untouched). "
                        "0 = no shadow phase: new generations promote "
                        "directly, the pre-rollout behavior")
    p.add_argument("--shadow-quota", type=int, default=64,
                   help="shadow-scored requests a candidate must pass "
                        "(divergence under --divergence-bound) before the "
                        "watcher promotes it to primary")
    p.add_argument("--divergence-bound", type=float, default=1e-3,
                   help="max |shadow - primary| score divergence; a "
                        "candidate breaching it is abandoned and poisoned")
    p.add_argument("--promotion-settle", type=float, default=300.0,
                   help="seconds after a promotion before it is considered "
                        "settled: the rollback parent unpins (becomes "
                        "evictable) and breaker-trip rollback monitoring for "
                        "that promotion stops (<= 0 = pin until the next "
                        "promote/rollback)")
    p.add_argument("--breaker-trip-bound", type=int, default=0,
                   help="circuit-breaker trips since promotion that trigger "
                        "automatic rollback to the parent generation "
                        "(0 disables rollback monitoring)")
    p.add_argument("--reload-max-attempts", type=int, default=3,
                   help="reload attempts (with exponential backoff) per "
                        "detected generation before it is marked poisoned "
                        "and skipped for good")
    p.add_argument("--reload-backoff", type=float, default=0.2,
                   help="initial retry backoff seconds for a failed reload")
    p.add_argument("--max-model-versions", type=int, default=2,
                   help="resident model generations (primary + candidates "
                        "pinnable via X-Model-Version)")
    p.add_argument("--feedback-spool", default=None,
                   help="directory for the streaming feedback spool: scored "
                        "requests joined with labels reported via "
                        "POST /v1/feedback land here as sealed JSONL "
                        "segments for photon-tpu-game-streaming to consume "
                        "(unset = feedback disabled)")
    p.add_argument("--feedback-sample-fraction", type=float, default=1.0,
                   help="fraction of scored requests retained for the label "
                        "join (deterministic fractional sampling)")
    p.add_argument("--feedback-tenant-fractions", default=None,
                   help="per-tenant sampling overrides, e.g. 'abuser=0.01,"
                        "partner=1.0'")
    p.add_argument("--feedback-segment-records", type=int, default=256,
                   help="seal a spool segment after this many records")
    p.add_argument("--feedback-segment-age", type=float, default=5.0,
                   help="seal a non-empty spool segment after this many "
                        "seconds (bounds label->consumable latency)")
    p.add_argument("--feedback-join-ttl", type=float, default=300.0,
                   help="seconds a scored request waits for its label before "
                        "the pending join is dropped")
    p.add_argument("--otlp-endpoint", default=None,
                   help="base URL of an OTLP/HTTP collector accepting JSON "
                        "(spans POST to <endpoint>/v1/traces, metrics to "
                        "<endpoint>/v1/metrics). Export is bounded-queue + "
                        "drop-and-count: a dead collector degrades "
                        "observability, never scoring")
    p.add_argument("--otlp-metrics-interval", type=float, default=15.0,
                   help="seconds between registry-snapshot exports to the "
                        "collector (0 = spans only)")
    p.add_argument("--slo-gate", action="store_true",
                   help="subscribe the rollout watcher to SLO burn state: a "
                        "paging burn on availability/latency aborts an "
                        "in-flight shadow, rolls back a promotion still in "
                        "its settle window (candidate poisoned, LATEST "
                        "repointed), and freezes further promotions until "
                        "the burn clears")
    p.add_argument("--verbose", action="store_true")
    return p


def resolve_model_dir(model_dir: str) -> str:
    """Follow a ``LATEST`` pointer file when present: its content names the
    current generation (a subdirectory of ``model_dir``, or an absolute
    path). Without one, ``model_dir`` itself is the generation — its
    metadata mtime is the change signal."""
    p = os.path.join(model_dir, "LATEST")
    if os.path.isfile(p):
        try:
            with open(p) as f:
                name = f.read().strip()
        except OSError:
            return model_dir
        if name:
            cand = name if os.path.isabs(name) else os.path.join(model_dir, name)
            if os.path.isdir(cand):
                return cand
    return model_dir


def _model_fingerprint(directory: str):
    from photon_tpu.io.model_io import METADATA_FILE

    try:
        mtime = os.path.getmtime(os.path.join(directory, METADATA_FILE))
    except OSError:
        mtime = None
    return (directory, mtime)


@dataclasses.dataclass
class RolloutOptions:
    """Watcher-side rollout policy. The defaults reproduce the pre-rollout
    watcher: no shadow phase (direct promote on detection), no rollback
    monitoring — plus retry-with-backoff on a failed reload (a transient
    store fault used to permanently skip a good generation)."""

    shadow_fraction: float = 0.0
    shadow_quota: int = 64
    divergence_bound: float = 1e-3
    breaker_trip_bound: int = 0  # 0 = rollback monitoring off
    max_reload_attempts: int = 3
    backoff_s: float = 0.2
    backoff_max_s: float = 5.0
    # SLO actuation (--slo-gate): a paging burn on any objective in
    # slo_objectives aborts shadows / rolls back unsettled promotions and
    # freezes further promotions until the burn clears. The quality
    # objectives (auc_drop, calibration_drift) ride along by default —
    # trackers without those rings ignore the names (record_event and
    # _slo_paging both degrade to no-ops on unknown objectives), and
    # trackers built with quality_objectives() make "the new model is
    # worse" page and actuate through the same gate.
    slo_gate: bool = False
    slo_objectives: tuple = (
        "availability", "latency_p99", "auc_drop", "calibration_drift",
    )


def _poison(publish_root: str, version: str, reason: str) -> None:
    from photon_tpu.io.model_io import mark_poisoned
    from photon_tpu.obs.metrics import registry

    try:
        mark_poisoned(publish_root, version, reason)
    except OSError:
        logger.exception("could not record poisoned generation %r", version)
    registry().counter("serve_generations_poisoned_total").inc()


def _observe_staleness(target: str) -> None:
    """Label-arrival → serving-promoted lag for a streaming generation:
    the promoted manifest records the oldest label it trained on; the gap
    to now IS the freshness the whole loop exists to bound."""
    from photon_tpu.io.model_io import load_generation_manifest
    from photon_tpu.obs.metrics import registry

    try:
        manifest = load_generation_manifest(target) or {}
    except (OSError, ValueError):
        return
    ts = (manifest.get("stream") or {}).get("oldestLabelTs")
    if ts is None:
        return
    import time

    lag = max(0.0, time.time() - float(ts))
    registry().gauge("model_staleness_s").set(lag)
    registry().histogram("model_staleness_hist_s").observe(lag)


def _try_delta_install(engine, target: str) -> bool:
    """In-place delta apply: when the detected generation is a delta layer
    and its base is already resident, register it via the store-overlay
    path — no disk load of the full model, no store rebuild, no warm-up.
    False means 'not applicable here' (full layer, base not resident, or
    entity growth) and the caller does the full resolved load."""
    from photon_tpu.io.model_io import delta_info, read_delta_rows

    info = delta_info(target)
    if not info or not info.get("base"):
        return False
    try:
        payload = read_delta_rows(
            target, engine._index_maps, engine._entity_indexes
        )
        engine.load_delta_version(payload["base"], payload, target)
        return True
    except Exception as exc:  # noqa: BLE001 — fall back to the full load
        logger.info(
            "in-place delta apply of %s not possible (%s); falling back to "
            "a full resolved load", target, exc,
        )
        return False


def _install_generation(engine, target: str, opts: RolloutOptions,
                        stop: threading.Event, publish_root: str) -> str:
    """Load one detected generation with retry+backoff. Returns 'shadow'
    (resident, mirroring traffic), 'promoted' (direct reload), 'poisoned'
    (attempts exhausted — never tried again), or 'stopped'.

    A delta micro-generation whose base is resident applies IN PLACE
    (per-entity row overlay onto the base's store — sub-second, no
    warm-up); anything else takes the full load of the RESOLVED model, so
    a delta chain loads correctly even on a cold start."""
    from photon_tpu.io.model_io import load_resolved_game_model
    from photon_tpu.obs.metrics import registry

    delay = opts.backoff_s
    attempts = max(int(opts.max_reload_attempts), 1)
    shadowing = opts.shadow_fraction > 0 and opts.shadow_quota > 0
    for attempt in range(1, attempts + 1):
        try:
            if _try_delta_install(engine, target):
                if shadowing:
                    engine.start_shadow(target, opts.shadow_fraction)
                    return "shadow"
                engine.promote(target)
                _observe_staleness(target)
                return "promoted"
            model = load_resolved_game_model(
                target, engine._index_maps, engine._entity_indexes,
                to_device=False, publish_root=publish_root,
            )
            if shadowing:
                engine.load_version(model, model_version=target)
                engine.start_shadow(target, opts.shadow_fraction)
                return "shadow"
            engine.reload(model, model_version=target)
            _observe_staleness(target)
            return "promoted"
        except Exception as exc:  # noqa: BLE001 — old model keeps serving
            logger.warning(
                "auto-reload from %s failed (attempt %d/%d): %s; model %r "
                "keeps serving",
                target, attempt, attempts, exc, engine.model_version,
            )
            registry().counter("serve_reload_retries_total").inc()
            if attempt >= attempts:
                _poison(
                    publish_root,
                    os.path.basename(target.rstrip("/")),
                    f"reload_failed: {exc}",
                )
                return "poisoned"
            if stop.wait(min(delay, opts.backoff_max_s)):
                return "stopped"
            delay = min(delay * 2.0, opts.backoff_max_s)
    return "stopped"


def _repoint_latest(publish_root: str, version: str) -> None:
    """After a rollback, move the on-disk LATEST pointer back to the parent
    so a restart (or any other consumer of the pointer) doesn't resurrect
    the demoted generation."""
    from photon_tpu.io.model_io import publish_latest_pointer

    name = os.path.basename(str(version).rstrip("/"))
    if os.path.isdir(os.path.join(publish_root, name)):
        try:
            publish_latest_pointer(publish_root, name)
        except OSError:
            logger.exception("could not repoint LATEST to %r", name)


def _slo_paging(engine, objectives) -> list:
    """Gated objectives currently in PAGE state; [] when healthy (or when
    the engine has no SLO tracker — the gate degrades to a no-op)."""
    out = []
    slo = getattr(engine, "slo", None)
    if slo is None:
        return out
    for name in objectives:
        try:
            if slo.state(name) == "page":
                out.append(name)
        except (KeyError, AttributeError):
            continue
    return out


def _trace_rollout_decision(action: str, version, reason: str) -> None:
    """Every SLO-gate decision is counted AND kept as a forced trace, so
    'why did my promotion abort' is answerable from /v1/traces alone."""
    from photon_tpu.obs.metrics import registry
    from photon_tpu.obs.trace import flight_recorder, mint_context, record_span

    registry().counter("serve_slo_gate_actions_total", action=action).inc()
    try:
        ctx = mint_context(forced=True)
        record_span(f"rollout/{action}", 0.0, parent="", context=ctx)
        flight_recorder().finish(
            ctx.trace_id, forced=True,
            meta={"action": action, "version": str(version),
                  "reason": reason},
        )
    except Exception:  # noqa: BLE001 — tracing never blocks the gate
        logger.exception("could not trace rollout decision %r", action)


def _reload_watcher(engine, model_dir: str, interval: float,
                    stop: threading.Event,
                    opts: Optional[RolloutOptions] = None) -> None:
    """Poll ``model_dir`` for new generations and walk each through the
    rollout lifecycle: candidate → (shadow →) primary → possibly
    rolled-back.

    - A detected generation loads with retry+backoff; exhausted attempts
      poison it (skipped forever — a restart honors the poison list too).
    - With ``shadow_fraction > 0`` the candidate first mirrors a sample of
      live traffic; it promotes only after ``shadow_quota`` shadow scores
      stayed under ``divergence_bound``, and is abandoned + poisoned on a
      breach.
    - With ``breaker_trip_bound > 0`` a promoted generation whose
      breaker-trip delta crosses the bound is demoted back to its parent
      (engine rollback), poisoned, and LATEST is repointed to the parent.

    With ``slo_gate`` the watcher also subscribes to the engine's
    SLOTracker: a PAGING burn on a gated objective aborts an in-flight
    shadow (candidate poisoned), rolls back a promotion still inside its
    settle window (PR 8 rollback path: demote + poison + repoint LATEST),
    and freezes promotions until the burn clears — every decision traced
    (forced keep) and counted (``serve_slo_gate_actions_total``)."""
    from photon_tpu.io.model_io import is_poisoned
    from photon_tpu.obs.metrics import registry

    opts = opts or RolloutOptions()
    current = _model_fingerprint(resolve_model_dir(model_dir))
    candidate: Optional[str] = None
    frozen_reason: Optional[str] = None
    while not stop.wait(interval):
        paging = (
            _slo_paging(engine, opts.slo_objectives) if opts.slo_gate else []
        )
        if opts.slo_gate:
            # Freeze lifecycle: any page freezes promotions; the freeze
            # clears only when every gated objective stops paging (the
            # short burn window is what makes that prompt).
            if frozen_reason is not None and not paging:
                logger.info(
                    "SLO burn cleared (%s); promotions unfrozen",
                    frozen_reason,
                )
                registry().gauge("serve_promotions_frozen").set(0)
                _trace_rollout_decision(
                    "unfreeze", engine.model_version, frozen_reason
                )
                frozen_reason = None
            elif paging and frozen_reason is None:
                frozen_reason = "slo_page: " + ",".join(paging)
                logger.warning(
                    "SLO paging (%s); promotions frozen", frozen_reason
                )
                registry().gauge("serve_promotions_frozen").set(1)
                _trace_rollout_decision(
                    "freeze", engine.model_version, frozen_reason
                )
        if paging and candidate is not None:
            # Paging during shadow: the candidate is guilty until proven
            # innocent — abort the promotion path and poison it.
            reason = "slo_page: " + ",".join(paging)
            engine.stop_shadow()
            logger.warning(
                "candidate %r aborted by SLO gate: %s", candidate, reason
            )
            _poison(model_dir, os.path.basename(candidate.rstrip("/")),
                    reason)
            _trace_rollout_decision("shadow_abort", candidate, reason)
            candidate = None
        if paging and engine.promotion_in_window():
            # Paging during the settle window: unwind the promotion the
            # same way breaker trips do.
            reason = "slo_page: " + ",".join(paging)
            demoted = engine.rollback(reason)
            if demoted is not None:
                _poison(model_dir,
                        os.path.basename(str(demoted).rstrip("/")), reason)
                _repoint_latest(model_dir, engine.model_version)
                current = _model_fingerprint(resolve_model_dir(model_dir))
                _trace_rollout_decision("slo_rollback", demoted, reason)
        # Shadow-phase verdicts for the current candidate, if any.
        if candidate is not None:
            st = engine.shadow_stats()
            if st["version"] is None:
                candidate = None  # cleared elsewhere (manual promote/stop)
            elif st["max_divergence"] > opts.divergence_bound:
                engine.stop_shadow()
                reason = f"shadow_divergence: {st['max_divergence']:.6g}"
                logger.warning(
                    "candidate %r abandoned: %s", candidate, reason
                )
                _poison(model_dir, os.path.basename(candidate.rstrip("/")),
                        reason)
                candidate = None
            elif st["count"] >= opts.shadow_quota:
                if frozen_reason is not None:
                    # Quota met but promotions are frozen: hold the
                    # candidate in shadow; it promotes after unfreeze.
                    registry().counter(
                        "serve_promotions_frozen_held_total"
                    ).inc()
                else:
                    logger.info(
                        "candidate %r passed shadow quota (%d scores, max "
                        "divergence %.3g); promoting",
                        candidate, st["count"], st["max_divergence"],
                    )
                    engine.promote(candidate)
                    _observe_staleness(candidate)
                    candidate = None
        # Post-promotion health: breaker-trip delta since the promotion.
        if opts.breaker_trip_bound > 0:
            trips = engine.trips_since_promotion()
            if trips >= opts.breaker_trip_bound:
                demoted = engine.rollback(f"breaker_trips: {trips}")
                if demoted is not None:
                    _poison(model_dir,
                            os.path.basename(str(demoted).rstrip("/")),
                            f"breaker_trips: {trips}")
                    _repoint_latest(model_dir, engine.model_version)
                    current = _model_fingerprint(resolve_model_dir(model_dir))
        # New-generation detection.
        target = resolve_model_dir(model_dir)
        fp = _model_fingerprint(target)
        if fp == current:
            continue
        if frozen_reason is not None:
            # Frozen: leave ``current`` untouched so the generation is
            # picked up on the first poll after the burn clears.
            registry().counter("serve_promotions_frozen_held_total").inc()
            continue
        current = fp
        name = os.path.basename(target.rstrip("/"))
        if is_poisoned(model_dir, name):
            logger.warning(
                "ignoring poisoned generation %r (see %s)", name, model_dir
            )
            continue
        logger.info("model change detected: loading %s", target)
        outcome = _install_generation(engine, target, opts, stop, model_dir)
        if outcome == "shadow":
            candidate = target
        elif outcome == "stopped":
            return


def make_handler(engine, artifacts_dir=None):
    """Back-compat factory: the in-process HTTP handler over ``engine``."""
    return make_http_handler(LocalBackend(engine))


def _admission_config(args) -> AdmissionConfig:
    return AdmissionConfig(
        default_qps=args.tenant_default_qps,
        default_burst=args.tenant_default_burst,
        tenant_qps=parse_tenant_rates(args.tenant_qps),
        tenant_burst=parse_tenant_rates(args.tenant_burst),
        batch_queue_fraction=args.batch_queue_fraction,
    )


def _serve_config(args) -> ServeConfig:
    return ServeConfig(
        max_batch_size=args.max_batch_size,
        max_delay_ms=args.max_delay_ms,
        queue_cap=args.queue_cap,
        hot_bytes=int(args.hot_bytes_mb * (1 << 20)),
        default_deadline_ms=args.deadline_ms,
        admission=_admission_config(args),
        max_versions=args.max_model_versions,
        shadow_fraction=args.shadow_fraction,
        promotion_settle_s=args.promotion_settle,
    )


def _rollout_options(args) -> RolloutOptions:
    return RolloutOptions(
        shadow_fraction=args.shadow_fraction,
        shadow_quota=args.shadow_quota,
        divergence_bound=args.divergence_bound,
        breaker_trip_bound=args.breaker_trip_bound,
        max_reload_attempts=args.reload_max_attempts,
        backoff_s=args.reload_backoff,
        slo_gate=bool(getattr(args, "slo_gate", False)),
    )


def _install_otlp(args, service_name: str):
    """``--otlp-endpoint`` wiring, AFTER begin_run (tracer sinks survive
    the reset, registry instruments do not). Returns the exporter or
    None."""
    from photon_tpu.obs.export import maybe_install_exporter

    return maybe_install_exporter(
        getattr(args, "otlp_endpoint", None), service_name,
        metrics_interval_s=float(
            getattr(args, "otlp_metrics_interval", 0.0) or 0.0
        ),
    )


def _close_otlp(exporter) -> None:
    if exporter is None:
        return
    from photon_tpu.obs.export import uninstall_exporter

    try:
        exporter.export_metrics()
        exporter.flush(timeout_s=3.0)
    except Exception:  # noqa: BLE001 — shutdown export is best-effort
        logger.exception("final OTLP export failed")
    uninstall_exporter()


def _telemetry_max_bytes(args):
    mb = float(args.telemetry_max_mb or 0.0)
    return int(mb * (1 << 20)) if mb > 0 else None


def _start_background(args, engine, stop: threading.Event) -> None:
    """Reload watcher + periodic telemetry flusher, both deployment shapes."""
    if args.reload_poll_interval and args.reload_poll_interval > 0:
        threading.Thread(
            target=_reload_watcher,
            args=(engine, args.model_input_dir, args.reload_poll_interval,
                  stop, _rollout_options(args)),
            name="model-reload-watcher",
            daemon=True,
        ).start()
    if args.telemetry_out and args.telemetry_flush_interval > 0:
        from photon_tpu.obs.report import collect_run_records, write_run_report

        max_bytes = _telemetry_max_bytes(args)

        def _flush_loop():
            while not stop.wait(args.telemetry_flush_interval):
                try:
                    write_run_report(
                        args.telemetry_out,
                        collect_run_records("game_serving"),
                        max_bytes=max_bytes,
                    )
                except Exception:  # noqa: BLE001 — telemetry never kills serving
                    logger.exception("periodic telemetry flush failed")

        threading.Thread(
            target=_flush_loop, name="telemetry-flush", daemon=True
        ).start()


def _attach_feedback(args, engine) -> None:
    """Wire the streaming feedback spool (engine owns its lifecycle)."""
    if not getattr(args, "feedback_spool", None):
        return
    from photon_tpu.stream.spool import FeedbackSpool, SpoolConfig

    fractions = {}
    if args.feedback_tenant_fractions:
        for part in args.feedback_tenant_fractions.split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                fractions[k.strip()] = float(v)
    spool = FeedbackSpool(args.feedback_spool, SpoolConfig(
        segment_max_records=args.feedback_segment_records,
        segment_max_age_s=args.feedback_segment_age,
        sample_fraction=args.feedback_sample_fraction,
        tenant_fractions=fractions,
        join_ttl_s=args.feedback_join_ttl,
    ))
    spool.start_auto_flush()
    engine.attach_feedback(spool)
    logger.info("feedback spool attached at %s", args.feedback_spool)


def _load_engine(args, config: ServeConfig):
    model_dir = resolve_model_dir(args.model_input_dir)
    logger.info("loading + warming model from %s", model_dir)
    artifacts = args.model_artifacts_dir
    if artifacts is None and model_dir != args.model_input_dir:
        # LATEST resolved to a generation subdir; the artifacts live
        # beside the generations, in the publication root.
        artifacts = args.model_input_dir
    engine = load_engine(model_dir, artifacts_dir=artifacts, config=config)
    _attach_feedback(args, engine)
    return engine


def _startup_banner(engine, host, port, workers: int) -> None:
    print(json.dumps({
        "serving": True,
        "host": host,
        "port": port,
        "workers": workers,
        "maxBatchSize": engine.max_batch,
        "modelVersion": engine.model_version,
    }), flush=True)


def _run_multiprocess(args):
    """The traffic shape: fork N workers FIRST (single-threaded, jax not
    yet initialized — fork safety), then build the engine and serve the
    scorer IPC socket from this process."""
    from photon_tpu.obs import begin_run, finalize_run_report

    frontend = ServingFrontend(
        args.host, args.port, args.workers,
        scorer_endpoint=args.scorer_endpoint,
    )
    frontend.fork_workers()  # before any jax init, see ServingFrontend
    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()

    # Handlers go in BEFORE the (slow) engine warm-up: a SIGTERM during
    # warm-up must still reach frontend.shutdown(), or the forked workers
    # would outlive the parent as orphans.
    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    begin_run()
    exporter = _install_otlp(args, "photon-tpu-serving")
    try:
        engine = _load_engine(args, _serve_config(args))
    except BaseException:
        frontend.shutdown()
        raise
    frontend.start_scorer(engine)
    _start_background(args, engine, stop)
    _startup_banner(engine, frontend.host, frontend.port, args.workers)
    try:
        while not stop.wait(0.5):
            frontend.poll_workers()
            if frontend.live_workers() == 0:
                logger.error("all serve workers exited; shutting down")
                break
    finally:
        stop.set()
        frontend.shutdown()  # workers drain first: no new admissions
        engine.close(drain=True)  # then score out what's queued
        finalize_run_report(
            "game_serving", path=args.telemetry_out,
            max_bytes=_telemetry_max_bytes(args),
        )
        _close_otlp(exporter)
        print(json.dumps({
            "serving": False,
            "stats": engine.stats(),
            "workerExits": {str(k): v for k, v in frontend.worker_exits.items()},
        }))


def _run_inprocess(args):
    from photon_tpu.obs import begin_run, finalize_run_report

    begin_run()
    exporter = _install_otlp(args, "photon-tpu-serving")
    engine = _load_engine(args, _serve_config(args))
    server = ThreadingHTTPServer(
        (args.host, args.port), make_handler(engine)
    )
    server.daemon_threads = True
    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    _start_background(args, engine, stop)
    _startup_banner(
        engine, server.server_address[0], server.server_address[1], 0
    )
    try:
        server.serve_forever(poll_interval=0.2)
    finally:
        stop.set()
        engine.close(drain=True)
        server.server_close()
        finalize_run_report(
            "game_serving", path=args.telemetry_out,
            max_bytes=_telemetry_max_bytes(args),
        )
        _close_otlp(exporter)
        print(json.dumps({"serving": False, "stats": engine.stats()}))


def run(args):
    setup_logging(args.verbose)
    from photon_tpu.utils import resources

    # Host RSS watchdog: under memory pressure the micro-batcher's
    # admission cap tightens (shed by backpressure, not by OOM-killer).
    resources.start_watchdog()
    if args.workers and args.workers > 0:
        _run_multiprocess(args)
    else:
        _run_inprocess(args)


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
