"""Name-and-term feature bags driver: scan input data → distinct feature
(name, term) sets per feature bag, saved as text files.

Parity target: reference ``NameAndTermFeatureBagsDriver``
(photon-client data/avro/NameAndTermFeatureBagsDriver.scala:196) +
``NameAndTermFeatureMapUtils.saveNameAndTermsAsTextFiles`` /
``readNameAndTermFeatureMapFromTextFiles``
(data/avro/NameAndTermFeatureMapUtils.scala): one directory per feature bag
under the root output directory, containing ``name<TAB>term`` lines. These
text bags are the non-PalDB path for building feature index maps
(GameDriver.prepareFeatureMapsDefault, cli/game/GameDriver.scala:190-217).
"""

from __future__ import annotations

import argparse
import glob as globlib
import os
from typing import Dict, List, Sequence, Set, Tuple

from photon_tpu.cli.common import setup_logging
from photon_tpu.data.index_map import IndexMap
from photon_tpu.io.data_reader import read_avro_rows
from photon_tpu.utils.io_utils import (
    date_range_from_specs,
    process_output_dir,
    resolve_range_paths,
)

# Reference NameAndTerm.STRING_DELIMITER is "\t".
DELIMITER = "\t"


def save_name_and_terms(output_dir: str, bag: str,
                        name_terms: Set[Tuple[str, str]]) -> str:
    """Write one bag's distinct (name, term) set as text
    (NameAndTermFeatureMapUtils.saveAsTextFiles layout: <root>/<bag>/...)."""
    bag_dir = os.path.join(output_dir, bag)
    os.makedirs(bag_dir, exist_ok=True)
    path = os.path.join(bag_dir, "part-00000")
    with open(path, "w") as f:
        for name, term in sorted(name_terms):
            f.write(f"{name}{DELIMITER}{term}\n")
    return path


def load_name_and_terms(output_dir: str, bag: str) -> List[Tuple[str, str]]:
    """Read a bag's (name, term) set back
    (NameAndTermFeatureMapUtils.readNameAndTermRDDFromTextFiles)."""
    out: List[Tuple[str, str]] = []
    for path in sorted(globlib.glob(os.path.join(output_dir, bag, "part-*"))):
        with open(path) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                parts = line.split(DELIMITER)
                if len(parts) == 2:
                    out.append((parts[0], parts[1]))
                elif len(parts) == 1:
                    out.append((parts[0], ""))
                else:
                    raise ValueError(
                        f"Cannot parse name-and-term line {line!r} in {path}"
                    )
    return out


def index_map_from_text_bags(output_dir: str, bags: Sequence[str],
                             add_intercept: bool = True) -> IndexMap:
    """Build one feature IndexMap from the union of text bags
    (GameDriver.prepareFeatureMapsDefault role)."""
    keys = []
    for bag in bags:
        for name, term in load_name_and_terms(output_dir, bag):
            keys.append(IndexMap.key(name, term))
    return IndexMap.build(keys, add_intercept=add_intercept)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("name-and-term-feature-bags")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--input-data-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd over daily-format input dirs")
    p.add_argument("--input-data-days-range", default=None,
                   help="start-end days ago over daily-format input dirs")
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-bags-keys", nargs="+", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--verbose", action="store_true")
    return p


def run(args) -> Dict[str, int]:
    setup_logging(args.verbose)
    date_range = date_range_from_specs(
        args.input_data_date_range, args.input_data_days_range
    )
    paths = resolve_range_paths(args.input_data_directories, date_range)
    process_output_dir(args.root_output_directory, args.override_output_directory)

    bag_sets: Dict[str, Set[Tuple[str, str]]] = {
        bag: set() for bag in args.feature_bags_keys
    }
    for row in read_avro_rows(paths):
        for bag, name_terms in bag_sets.items():
            for f in row.get(bag) or []:
                name_terms.add((f["name"], f.get("term") or ""))
    counts: Dict[str, int] = {}
    for bag, name_terms in bag_sets.items():
        save_name_and_terms(args.root_output_directory, bag, name_terms)
        counts[bag] = len(name_terms)
    return counts


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    counts = run(args)
    for bag, n in counts.items():
        print(f"{bag}: {n} distinct name-and-term features")


if __name__ == "__main__":
    main()
