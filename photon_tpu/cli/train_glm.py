"""Legacy single-GLM training driver.

Parity target: reference legacy ``Driver`` (photon-client Driver.scala:60-558)
with its INIT→PREPROCESSED→TRAINED→VALIDATED stage machine (DriverStage
.scala:20-55): read data (Avro or LIBSVM) → summarize/normalize → λ sweep
with warm start (ModelTraining.trainGeneralizedLinearModel role,
photon-api ModelTraining.scala:54-200) → validate per λ → select best by the
task's default metric → write models (text + Avro) + lifecycle events.
"""

from __future__ import annotations

import argparse
import enum
import json
import logging
import os
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.cli.common import add_validation_arg, setup_logging, task_of
from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.index_map import IndexMap
from photon_tpu.data.normalization import build_normalization_context
from photon_tpu.data.stats import compute_feature_stats
from photon_tpu.evaluation.metrics_map import (
    metrics_map,
    sanitize_for_json,
    selection_metric,
)
from photon_tpu.io.data_reader import FeatureShardConfig, read_merged
from photon_tpu.io.libsvm import read_libsvm
from photon_tpu.io.model_io import publish_latest_pointer, save_game_model
from photon_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_SCHEMA
from photon_tpu.io.avro import write_avro_records
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.game import FixedEffectModel, GameModel
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.factory import OptimizerSpec, make_optimizer
from photon_tpu.types import NormalizationType, OptimizerType, TaskType
from photon_tpu.utils.events import (
    EventEmitter,
    optimization_log_event,
    setup_event,
    training_finish_event,
    training_start_event,
)

class DriverStage(enum.Enum):
    """Reference DriverStage.scala:20-55 state machine."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("train-glm")
    p.add_argument("--training-data", required=True,
                   help="Avro path/dir/glob, or LIBSVM text file with --format libsvm")
    p.add_argument("--validation-data", default=None)
    p.add_argument("--format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="LOGISTIC_REGRESSION", choices=[t.name for t in TaskType])
    p.add_argument("--optimizer", default="LBFGS", choices=[o.name for o in OptimizerType])
    p.add_argument("--regularization-weights", default="0.1,1,10,100")
    p.add_argument(
        "--regularization-type", default=None,
        choices=["NONE", "L1", "L2", "ELASTIC_NET"],
        help="reference REGULARIZATION_TYPE_OPTION: NONE ignores the "
             "weights, L1/L2 force the elastic-net alpha to 1/0, "
             "ELASTIC_NET uses --elastic-net-alpha as given",
    )
    p.add_argument("--elastic-net-alpha", type=float, default=0.0)
    p.add_argument("--max-iterations", type=int, default=None)
    p.add_argument("--tolerance", type=float, default=None)
    p.add_argument(
        "--optimization-state-tracker",
        action=argparse.BooleanOptionalAction, default=True,
        help="per-iteration (loss, |grad|) tracker rings "
             "(OPTIMIZATION_STATE_TRACKER_OPTION)",
    )
    p.add_argument(
        "--validate-per-iteration", action="store_true",
        help="compute the validation MetricsMap at EVERY optimizer "
             "iteration count (reference VALIDATE_PER_ITERATION; replays "
             "the deterministic solve at increasing max-iter — expensive, "
             "like the reference's warning says)",
    )
    p.add_argument(
        "--feature-dimension", type=int, default=None,
        help="explicit feature-space dimension for libsvm input "
             "(FEATURE_DIMENSION option; inferred when omitted)",
    )
    p.add_argument("--normalization", default="NONE", choices=[t.name for t in NormalizationType])
    p.add_argument("--intercept", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--coefficient-box", default=None,
                   help="lower,upper box constraint applied to all coefficients")
    p.add_argument("--selected-features-file", default=None,
                   help="Avro file of FeatureNameTermAvro records; only "
                        "these features are used for training (reference "
                        "SELECTED_FEATURES_FILE, avro format only)")
    p.add_argument(
        "--constraint-string",
        default=None,
        help="JSON array of per-feature bounds "
             '[{"name": ..., "term": ..., "lowerBound": ..., "upperBound": ...}] '
             "with GLMSuite wildcard semantics (reference "
             "io/deprecated/GLMSuite.scala:190-260)",
    )
    p.add_argument(
        "--compute-variance",
        nargs="?",
        const="SIMPLE",
        default="NONE",
        choices=["NONE", "SIMPLE", "FULL"],
        help="coefficient variances (bare flag = SIMPLE diag-inverse; FULL = "
             "Cholesky inverse diagonal)",
    )
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="dotted paths of event listener callables")
    p.add_argument("--event-listener", action="append", default=[],
                   dest="event_listener",
                   help="register one event listener by path "
                        "('pkg.module:attr'); repeatable")
    p.add_argument("--telemetry-out", default=None,
                   help="write the unified run report (spans + metrics + "
                        "per-lambda solver diagnostics) as schema-stable "
                        "JSONL to this path")
    p.add_argument("--summarization-output-dir", default=None,
                   help="write per-feature summary statistics as "
                        "FeatureSummarizationResultAvro "
                        "(writeBasicStatistics role)")
    p.add_argument("--stream-ingest-chunk-rows", type=int, default=0,
                   help="avro format: multi-pass streaming ingest "
                        "(io/pipeline.py) — pass 1 decodes container "
                        "blocks once (chunks of this many rows, teed into "
                        "a byte-budgeted host replay cache) while distinct-"
                        "scanning the feature space; pass 2 replays decoded "
                        "chunks through assemble + host→device pipeline "
                        "stages, concatenating on device — decode is never "
                        "paid twice and host RAM never holds the assembled "
                        "dataset")
    p.add_argument("--replay-cache-mb", type=int, default=1024,
                   help="host byte budget (MiB) for the decoded-chunk "
                        "replay cache; when the stream outgrows it the "
                        "cache spills and later passes re-stream from disk "
                        "(host memory stays bounded either way)")
    add_validation_arg(p)
    from photon_tpu.cli.common import add_active_set_args, add_out_of_core_args

    add_active_set_args(p)
    add_out_of_core_args(p)
    p.add_argument("--checkpoint-dir", default=None,
                   help="λ-sweep checkpoint/resume directory: one durable "
                        "step per completed λ (results + the warm-start "
                        "vector), written through the atomic checkpoint "
                        "machinery; a killed run resumes at the next λ. "
                        "Resumes automatically when state exists")
    p.add_argument("--resume", action="store_true",
                   help="resume an interrupted λ sweep from --checkpoint-dir "
                        "(requires checkpoint state to exist; auto-resume "
                        "merely uses it when present)")
    p.add_argument("--checkpoint-keep-last", type=int, default=None,
                   help="keep only the newest K λ-step files (pruned after "
                        "each save; also pruned before the disk-full "
                        "retry). NB a resumed sweep replays pruned λs. "
                        "Default: keep everything, or "
                        "PHOTON_TPU_CHECKPOINT_KEEP_LAST")
    p.add_argument("--verbose", action="store_true")
    return p


def _selected_features_index_map(args) -> Optional[IndexMap]:
    """SELECTED_FEATURES_FILE role (PhotonMLCmdLineParser.scala:203-205,
    GLMSuite.getSelectedFeatureSetFromFile): an Avro file of
    FeatureNameTermAvro records restricting the training feature space.
    Features outside the set are dropped at ingest (the reader masks
    features absent from a provided index map)."""
    if not args.selected_features_file:
        return None
    if args.format == "libsvm":
        raise ValueError(
            "--selected-features-file applies to the avro format "
            "(features are name/term keyed)"
        )
    from photon_tpu.io.avro import AvroReader

    keys = set()
    with AvroReader(args.selected_features_file) as r:
        for rec in r:
            keys.add(IndexMap.key(rec["name"], rec.get("term") or ""))
    if not keys:
        raise ValueError(
            f"no features in {args.selected_features_file}"
        )
    return IndexMap.build(sorted(keys), add_intercept=args.intercept)


def _stream_load_avro(args, path: str, index_map: Optional[IndexMap]):
    """Streaming multi-pass avro load (decode once, replay from a
    byte-budgeted host cache):

    pass 1  stream_avro_columnar decodes container blocks into ColumnarRows
            chunks, teed into a ChunkReplayCache; the same pass distinct-
            scans feature keys in global first-occurrence order — the exact
            IndexMap the slurping reader builds (skipped when the map is
            supplied, e.g. --selected-features-file or validation data).
    pass 2  replays decoded chunks (re-streams from disk if the cache
            spilled its byte budget) through the assemble + h2d pipeline
            stages (io/pipeline.py), concatenating on device — each chunk's
            transfer overlaps earlier chunks' placement via async dispatch,
            and host RAM never holds the assembled dataset.
    """
    from photon_tpu.io.columnar import stream_avro_columnar
    from photon_tpu.io.data_reader import _expand_paths
    from photon_tpu.io.pipeline import (
        ChunkReplayCache,
        assemble_host_batches,
        columnar_nbytes,
        device_chunks_from,
        materialize_game_batch,
    )

    chunk_rows = args.stream_ingest_chunk_rows
    paths = _expand_paths([path])
    cache = ChunkReplayCache(
        lambda: stream_avro_columnar(paths, chunk_rows),
        byte_budget=args.replay_cache_mb << 20,
        nbytes=columnar_nbytes,
    )
    imap = index_map
    if imap is None:
        seen: Dict[str, None] = {}
        for cols in cache:
            ids = [
                cols.bags[b].key_ids
                for b in ("features",)
                if b in cols.bags and cols.bags[b].key_ids.size
            ]
            if ids:
                for i in np.unique(np.concatenate(ids)):
                    seen.setdefault(cols.intern[i], None)
        imap = IndexMap.build(seen, add_intercept=args.intercept)
    cfg = {
        "features": FeatureShardConfig(
            feature_bags=["features"], has_intercept=args.intercept
        )
    }
    batch = materialize_game_batch(
        device_chunks_from(
            lambda: assemble_host_batches(
                iter(cache), cfg, {"features": imap}
            ),
            telemetry_label="train-ingest",
        )
    )
    log = logging.getLogger("photon_tpu.train_glm")
    log.info(
        "streaming ingest: decode passes=%d replay passes=%d cache=%s",
        cache.source_passes, cache.replay_passes,
        "spilled" if cache.spilled
        else f"{cache.cached_bytes >> 20} MiB held",
    )
    cache.close()  # the batch is materialized; delete any disk spool now
    return batch.labeled_batch("features"), imap


def _load(args, path: Optional[str], index_map=None):
    if path is None:
        return None, index_map
    if args.format == "libsvm":
        X, y = read_libsvm(path, dim=args.feature_dimension)
        if args.intercept:
            X = np.concatenate([X, np.ones((X.shape[0], 1), np.float32)], axis=1)
        imap = index_map or IndexMap.build(
            [str(j + 1) for j in range(X.shape[1] - (1 if args.intercept else 0))],
            add_intercept=args.intercept,
        )
        return LabeledBatch(jnp.asarray(y), jnp.asarray(X)), imap
    if int(getattr(args, "stream_ingest_chunk_rows", 0) or 0) > 0:
        return _stream_load_avro(args, path, index_map)
    cfg = {"features": FeatureShardConfig(feature_bags=["features"], has_intercept=args.intercept)}
    batch, imaps, _ = read_merged(
        [path], cfg, index_maps=None if index_map is None else {"features": index_map}
    )
    return batch.labeled_batch("features"), imaps["features"]


def run(args) -> Dict:
    setup_logging(args.verbose)
    from photon_tpu.obs import begin_run, finalize_run_report, span

    begin_run()  # fresh spans / metrics / phase records for THIS run
    from photon_tpu.utils import resources as _resources

    # Host RSS watchdog: inert without a detectable limit; under pressure
    # pipeline depths tighten, and the λ boundary below fails cleanly at the
    # hard level instead of catching the OOM-killer's SIGKILL.
    _resources.start_watchdog()
    if getattr(args, "re_active_set", False):
        logging.getLogger(__name__).warning(
            "--re-active-set is a no-op for the single-GLM driver (no "
            "random-effect coordinates); it only affects GAME training"
        )
    if getattr(args, "re_device_budget_mb", None):
        logging.getLogger(__name__).warning(
            "--re-device-budget-mb is a no-op for the single-GLM driver "
            "(no random-effect coordinates); it only affects GAME training"
        )
    task = task_of(args)
    stage = DriverStage.INIT
    emitter = EventEmitter()
    for name in list(args.event_listeners) + list(
        getattr(args, "event_listener", [])
    ):
        emitter.register_by_name(name)
    emitter.emit(setup_event(driver="train_glm", task=args.task,
                             optimizer=args.optimizer))

    if args.validate_per_iteration and args.validation_data is None:
        raise ValueError(
            "--validate-per-iteration requires --validation-data"
        )
    train, imap = _load(args, args.training_data,
                        _selected_features_index_map(args))
    valid, _ = _load(args, args.validation_data, imap)
    from photon_tpu.data.validators import DataValidationType, validate_labeled_batch

    validation_mode = DataValidationType[args.data_validation]
    validate_labeled_batch(train, task, validation_mode)
    if valid is not None:
        validate_labeled_batch(valid, task, validation_mode)
    icpt = imap.get_index(IndexMap.INTERCEPT) if args.intercept else None
    if icpt is not None and icpt < 0:
        icpt = None

    norm = None
    norm_type = NormalizationType[args.normalization]
    if norm_type != NormalizationType.NONE or args.summarization_output_dir:
        stats = compute_feature_stats(train, icpt)
        if norm_type != NormalizationType.NONE:
            norm = build_normalization_context(
                norm_type, stats.mean, stats.std, stats.abs_max, icpt
            )
        if args.summarization_output_dir:
            from photon_tpu.io.model_io import write_basic_statistics

            write_basic_statistics(
                stats, imap,
                os.path.join(args.summarization_output_dir, "part-00000.avro"),
            )
    stage = DriverStage.PREPROCESSED

    box = None
    if args.coefficient_box:
        lo, hi = (float(x) for x in args.coefficient_box.split(","))
        d = train.dim
        box = (jnp.full((d,), lo, jnp.float32), jnp.full((d,), hi, jnp.float32))
    if args.constraint_string:
        from photon_tpu.data.constraints import constraint_bound_vectors

        if box is not None:
            raise ValueError(
                "--constraint-string and --coefficient-box are exclusive"
            )
        bounds = constraint_bound_vectors(
            args.constraint_string, imap, train.dim, icpt
        )
        if bounds is not None:
            box = (jnp.asarray(bounds[0]), jnp.asarray(bounds[1]))

    # REGULARIZATION_TYPE_OPTION semantics (PhotonMLCmdLineParser.scala:
    # 100-116): NONE ignores the weights entirely; L1/L2 pin the
    # elastic-net mix; ELASTIC_NET takes the alpha as given.
    if args.regularization_type == "NONE":
        args.regularization_weights = "0"
    elif args.regularization_type == "L1":
        args.elastic_net_alpha = 1.0
    elif args.regularization_type == "L2":
        args.elastic_net_alpha = 0.0

    weights = sorted(float(x) for x in args.regularization_weights.split(","))
    weights.reverse()  # strongest first: warm start toward weaker reg
    loss = loss_for_task(task)
    emitter.emit(training_start_event(task=task.value, weights=weights))

    from photon_tpu.algorithm.solve_cache import default_cache
    from photon_tpu.utils.shutdown import (
        GracefulShutdown,
        handle_termination,
        shutdown_requested,
    )

    models: List[Dict] = []
    solver_diags: List = []
    solver_walls: List[float] = []
    w = jnp.zeros((train.dim,), jnp.float32)

    # λ-sweep checkpoint/resume: one step per completed λ through the atomic
    # checkpoint machinery (utils/checkpoint.py). The tag pins the sweep
    # configuration — a resumed run must be solving the SAME problem, or the
    # restored warm-start chain would silently change the results.
    ckpt_dir = args.checkpoint_dir
    ckpt_tag = "|".join([
        args.task, args.optimizer, f"{args.elastic_net_alpha:g}",
        ",".join(f"{lam:g}" for lam in weights),
    ])
    start_idx = 0
    if ckpt_dir and args.validate_per_iteration:
        raise ValueError(
            "--checkpoint-dir is incompatible with --validate-per-iteration "
            "(per-iteration replay handles are not persistable)"
        )
    if args.resume and not ckpt_dir:
        raise ValueError("--resume requires --checkpoint-dir")
    if ckpt_dir:
        from photon_tpu.utils.checkpoint import (
            LegacyCheckpointError,
            latest_step,
            load_checkpoint,
        )

        if args.resume and latest_step(ckpt_dir) is None:
            raise ValueError(f"--resume: no checkpoint state under {ckpt_dir}")
        log = logging.getLogger("photon_tpu.train_glm")
        state = step = None
        try:
            state, step = load_checkpoint(ckpt_dir)
        except FileNotFoundError:
            pass
        except LegacyCheckpointError as exc:
            log.warning("ignoring legacy checkpoint under %s: %s", ckpt_dir, exc)
        if state is not None:
            if state.get("tag") != ckpt_tag:
                log.warning(
                    "checkpoint under %s is for a different λ-sweep "
                    "configuration; starting fresh", ckpt_dir,
                )
            else:
                models = list(state["models"])
                solver_diags = list(state["solver_diags"])
                solver_walls = list(state["solver_walls"])
                w = state["w"]
                start_idx = step + 1
                log.info(
                    "resuming λ sweep from checkpoint: %d/%d weights done",
                    start_idx, len(weights),
                )
                from photon_tpu.obs import registry as _registry

                _registry().counter("glm_sweep_resumes_total").inc()

    for lam_idx, lam in enumerate(weights):
        if lam_idx < start_idx:
            continue  # restored from checkpoint
        objective = GLMObjective(
            loss=loss,
            l2_weight=(1.0 - args.elastic_net_alpha) * lam,
            l1_weight=args.elastic_net_alpha * lam,
            intercept_index=icpt,
            normalization=norm,
        )
        spec = OptimizerSpec(
            OptimizerType[args.optimizer], args.max_iterations, args.tolerance,
            box=box, track_history=args.optimization_state_tracker,
        )
        # λ solves route through the shared compiled-solver cache — same
        # semantics as make_optimizer, but retraces and hits are accounted
        # (and a repeated λ config reuses one executable).
        solve = default_cache().fe_solver(objective, spec)
        w0_lam = w
        t0 = time.monotonic()
        with span(f"glm/lambda{lam:g}"):
            with span("solve"):
                result = solve(w, train)
        solver_walls.append(time.monotonic() - t0)
        solver_diags.append(result)
        w = result.w  # warm start (ModelTraining.scala:162-200)
        w_model = norm.transformed_to_model_space(w) if norm is not None else w
        from photon_tpu.ops.variance import (
            coefficient_variances,
            normalize_variance_type,
        )

        variances = coefficient_variances(
            objective, w, train, normalize_variance_type(args.compute_variance)
        )
        models.append(
            {
                "lambda": lam,
                "w": w_model,
                "variances": variances,
                "loss": float(result.value),
                "iterations": int(result.iterations),
                "reason": result.convergence_reason.value,
                # Replay handles for --validate-per-iteration (stripped
                # from the serialized summary).
                "_objective": objective,
                "_spec": spec,
                "_w0": w0_lam,
            }
        )
        emitter.emit(
            optimization_log_event(
                reg_weight=lam, loss=float(result.value),
                iterations=int(result.iterations),
                convergence=result.convergence_reason.value,
            )
        )
        if ckpt_dir:
            from photon_tpu.utils import resources
            from photon_tpu.utils.checkpoint import save_checkpoint

            # Replay handles (_objective/_spec/_w0) are live closures, not
            # persistable — strip them; everything else (including the
            # OptimizeResult diagnostics) round-trips through the manifest.
            try:
                save_checkpoint(
                    ckpt_dir,
                    dict(
                        tag=ckpt_tag,
                        w=w,
                        models=[
                            {k: v for k, v in m.items() if not k.startswith("_")}
                            for m in models
                        ],
                        solver_diags=solver_diags,
                        solver_walls=solver_walls,
                    ),
                    lam_idx,
                    keep_last=args.checkpoint_keep_last,
                )
            except OSError as exc:
                # The writer already pruned + retried. A disk that stays
                # full costs resumability, not the sweep: the final model
                # summary still gets written at the end.
                if not resources.is_enospc(exc):
                    raise
                from photon_tpu.obs.metrics import registry

                registry().counter("checkpoint_write_failures_total").inc()
                logging.getLogger("photon_tpu.train_glm").warning(
                    "λ-sweep checkpoint at λ=%g failed even after pruning "
                    "(disk full under %s); continuing WITHOUT a checkpoint "
                    "for this λ: %s", lam, ckpt_dir, exc,
                )
        signum = shutdown_requested()
        if signum is not None:
            logging.getLogger("photon_tpu.train_glm").warning(
                "λ sweep stopping after λ=%g on signal %d", lam, signum
            )
            finalize_run_report(
                "train_glm", path=args.telemetry_out, emitter=emitter
            )
            raise GracefulShutdown(signum)
        # Same cooperative boundary handles hard host memory pressure: the
        # finished λ steps are already durable (when --checkpoint-dir is
        # set), so failing HERE is clean and resumable.
        from photon_tpu.utils import resources as _resources

        _resources.check_memory(f"train_glm λ={lam:g}")
    stage = DriverStage.TRAINED

    # Validation + model selection (Driver.computeAndLogModelMetrics:353 +
    # Driver.modelSelection:416 roles): every λ gets the task's FULL
    # MetricsMap (Evaluation.scala:31-128) — MAE/MSE/RMSE for regression,
    # AUPR/AUROC/peak-F1 for classifiers, per-datum log-likelihood + AIC
    # where defined — then the best model is picked by the task's
    # selection metric (ModelSelection.scala:36-63).
    log = logging.getLogger("photon_tpu.train_glm")
    best_idx = len(models) - 1
    if valid is not None:
        sel_name, larger_better = selection_metric(task)
        best_val = None
        for i, m in enumerate(models):
            margins = valid.margins(m["w"])
            mmap = metrics_map(
                task, margins, valid.label, coefficients=m["w"]
            )
            m["validation"] = mmap
            log.info("Model with lambda = %g:", m["lambda"])
            if args.validate_per_iteration:
                # VALIDATE_PER_ITERATION (Driver.scala:354-376): metrics at
                # every iteration count. The deterministic solver replayed
                # from the same warm start with max_iter=j reproduces the
                # tracker's state-j coefficients exactly; one compile per j.
                import dataclasses as _dc

                per_iter = []
                for j in range(1, int(m["iterations"]) + 1):
                    spec_j = _dc.replace(m["_spec"], max_iter=j)
                    res_j = make_optimizer(m["_objective"], spec_j)(
                        m["_w0"], train
                    )
                    w_j = (norm.transformed_to_model_space(res_j.w)
                           if norm is not None else res_j.w)
                    mm_j = metrics_map(task, valid.margins(w_j), valid.label,
                                       coefficients=w_j)
                    per_iter.append(mm_j)
                    for name in sorted(mm_j):  # Driver.scala:368-373 shape
                        log.info("Iteration: [%6d] Metric: [%s] value: %s",
                                 j, name, mm_j[name])
                m["per_iteration_validation"] = per_iter
            for name in sorted(mmap):  # Driver.scala:400-405 log shape
                log.info("Metric: [%s] value: %s", name, mmap[name])
            v = mmap[sel_name]
            if best_val is None or (
                v > best_val if larger_better else v < best_val
            ):
                best_val, best_idx = v, i
        log.info(
            "Regularization weight of the best model is: %g",
            models[best_idx]["lambda"],
        )
        stage = DriverStage.VALIDATED

    os.makedirs(args.output_dir, exist_ok=True)
    # Text models (IOUtils.writeModelsInText role): one file per λ.
    for m in models:
        path = os.path.join(args.output_dir, f"model-lambda-{m['lambda']:g}.txt")
        with open(path, "w") as f:
            f.write(f"# task={task.value} lambda={m['lambda']:g} loss={m['loss']:.6e}\n")
            wv = np.asarray(m["w"])
            for j in np.flatnonzero(np.abs(wv) > 0):
                key = imap.get_feature_name(int(j)) or str(j)
                f.write(f"{key}\t{wv[j]:.8g}\n")
    # Avro model output for the best model (BayesianLinearModelAvro).
    best = models[best_idx]
    game = GameModel(
        {
            "global": FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(best["w"], best["variances"]), task
                ),
                "features",
            )
        }
    )
    save_game_model(game, os.path.join(args.output_dir, "best"), {"features": imap})
    # fsync'd LATEST pointer: game_serving --reload-poll-interval follows
    # it, so a retrain hot-swaps into a live server with zero downtime.
    publish_latest_pointer(args.output_dir, "best")
    summary = {
        "best_lambda": best["lambda"],
        "models": [
            {k: v for k, v in m.items()
             if k not in ("w", "variances") and not k.startswith("_")}
            for m in models
        ],
        "stage": stage.name,
    }
    with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
        # Non-finite metrics (e.g. AIC at the n−k−1=0 pole) become null:
        # the bare token Infinity is not RFC-8259 JSON.
        json.dump(sanitize_for_json(summary), f, indent=2)
    emitter.emit(training_finish_event(best_lambda=best["lambda"]))
    finalize_run_report(
        "train_glm",
        path=args.telemetry_out,
        emitter=emitter,
        trackers=[{
            "label": "glm",
            # One tracker row per λ solve (the driver's CD-analogue: the
            # λ sweep IS its coordinate sequence).
            "tracker": {"global": solver_diags},
            "wall_times": {"global": solver_walls},
        }],
    )
    return summary


def main(argv=None):
    args = build_parser().parse_args(argv)
    from photon_tpu.utils.shutdown import GracefulShutdown, handle_termination

    try:
        with handle_termination():
            summary = run(args)
    except GracefulShutdown as exc:
        # Telemetry was finalized and the last completed λ is durable in
        # --checkpoint-dir; 128+signum is the conventional signal exit.
        raise SystemExit(128 + exc.signum) from exc
    print(json.dumps({"best_lambda": summary["best_lambda"]}))


if __name__ == "__main__":
    main()
