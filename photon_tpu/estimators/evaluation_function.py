"""GameEstimator ↔ hyperparameter-vector adapter.

Parity target: reference ``GameEstimatorEvaluationFunction`` (photon-client
estimators/GameEstimatorEvaluationFunction.scala:40-241): a GAME
optimization configuration is vectorized as, per coordinate sorted by id,
``log(regularization weight)`` — plus the elastic-net ``alpha`` when the
coordinate uses elastic net — and the evaluation of a candidate vector is a
full ``GameEstimator.fit`` on that configuration, returning the primary
validation metric (sign-flipped for maximization metrics so the tuner
always minimizes).

Differences from the reference, by design:
- log10 instead of ln (matches HyperparameterSerialization's LOG transform,
  VectorRescaling.scala:46 — the reference is internally inconsistent here;
  one base is as good as the other as long as pack/unpack agree).
- The adapter retains each candidate's trained ``GameResult`` so the driver
  can persist TUNED models without re-training.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.estimators.config import (
    GameOptimizationConfig,
    RegularizationConfig,
)
from photon_tpu.hyperparameter.search import SearchRange

# Reference defaults (GameEstimatorEvaluationFunction.scala:242-243).
DEFAULT_REG_WEIGHT_RANGE = (1e-4, 1e4)
DEFAULT_REG_ALPHA_RANGE = (0.0, 1.0)


@dataclasses.dataclass(frozen=True)
class _Slot:
    """One tunable scalar: (coordinate id, 'weight'|'alpha')."""

    coordinate_id: str
    kind: str


class GameEstimatorEvaluationFunction:
    """Callable mapping a hyperparameter vector (in transformed range space:
    log10-weight / raw alpha) to the primary validation metric.

    A coordinate contributes tunable dimensions following the reference's
    rule (GameTrainingDriver.scala:662-672): none if unregularized in the
    base config, log-weight if L1/L2, log-weight + alpha if elastic net
    (alpha > 0 in the base config).
    """

    def __init__(
        self,
        estimator,
        base_config: GameOptimizationConfig,
        batch,
        validation_batch,
        evaluation_suite,
        is_opt_max: bool,
        reg_weight_range: Tuple[float, float] = DEFAULT_REG_WEIGHT_RANGE,
        reg_alpha_range: Tuple[float, float] = DEFAULT_REG_ALPHA_RANGE,
    ):
        self.estimator = estimator
        self.base_config = base_config
        self.batch = batch
        self.validation_batch = validation_batch
        self.evaluation_suite = evaluation_suite
        self.direction = -1.0 if is_opt_max else 1.0

        self._slots: List[_Slot] = []
        lowers: List[float] = []
        uppers: List[float] = []
        for cid in sorted(base_config.reg):
            reg = base_config.reg[cid]
            if reg.weight <= 0.0:
                continue  # RegularizationType.NONE: not tuned
            self._slots.append(_Slot(cid, "weight"))
            lowers.append(math.log10(reg_weight_range[0]))
            uppers.append(math.log10(reg_weight_range[1]))
            if reg.alpha > 0.0:  # elastic net: tune the mixing too
                self._slots.append(_Slot(cid, "alpha"))
                lowers.append(reg_alpha_range[0])
                uppers.append(reg_alpha_range[1])
        self.search_range = SearchRange(np.asarray(lowers), np.asarray(uppers))
        self.results: List = []  # GameResult per evaluated candidate

    @property
    def dim(self) -> int:
        return len(self._slots)

    @property
    def names(self) -> List[str]:
        """One name per tunable dimension, e.g. ``global.weight`` (log10
        space), ``perUser.alpha`` — the keys used in observation JSON."""
        return [f"{s.coordinate_id}.{s.kind}" for s in self._slots]

    # --- configurationToVector / vectorToConfiguration ---

    def config_to_vector(self, config: GameOptimizationConfig) -> np.ndarray:
        if set(config.reg) != set(self.base_config.reg):
            raise ValueError(
                "configuration coordinates do not match the base configuration"
            )
        out = []
        for slot in self._slots:
            reg = config.reg[slot.coordinate_id]
            out.append(
                math.log10(reg.weight) if slot.kind == "weight" else reg.alpha
            )
        return np.asarray(out, float)

    def vector_to_config(self, x: np.ndarray) -> GameOptimizationConfig:
        if len(x) != self.dim:
            raise ValueError(f"dimension mismatch: {len(x)} != {self.dim}")
        reg = {cid: r for cid, r in self.base_config.reg.items()}
        for slot, v in zip(self._slots, np.asarray(x, float)):
            old = reg[slot.coordinate_id]
            if slot.kind == "weight":
                reg[slot.coordinate_id] = RegularizationConfig(
                    weight=float(10.0**v), alpha=old.alpha
                )
            else:
                reg[slot.coordinate_id] = RegularizationConfig(
                    weight=old.weight, alpha=float(v)
                )
        return GameOptimizationConfig(reg)

    # --- EvaluationFunction.apply ---

    def __call__(self, x: np.ndarray) -> float:
        config = self.vector_to_config(x)
        results = self.estimator.fit(
            self.batch,
            validation_batch=self.validation_batch,
            evaluation_suite=self.evaluation_suite,
            optimization_configs=[config],
        )
        result = results[0]
        self.results.append(result)
        return self.direction * self._primary_metric(result)

    def evaluate_batch(self, X: np.ndarray) -> List[float]:
        """Evaluate q candidate vectors together. Uses the vmapped
        one-program fast path (estimators/batched_tuning.py) when the setup
        is batchable; otherwise falls back to q sequential fits. Returns
        signed values in the tuner's minimization convention, matching
        ``__call__``."""
        X = np.asarray(X, float)
        fast = self._batched_evaluator()
        if fast is not None:
            return [self.direction * m for m in fast(X)]
        return [self(x) for x in X]

    def _batched_evaluator(self):
        if not hasattr(self, "_batched"):
            from photon_tpu.estimators.batched_tuning import build_batched_evaluator

            self._batched = build_batched_evaluator(
                self.estimator,
                self.base_config,
                self._slots,
                self.batch,
                self.validation_batch,
                self.evaluation_suite,
            )
        return self._batched

    def _primary_metric(self, result) -> float:
        if result.metrics is None:
            raise ValueError(
                "hyperparameter tuning requires validation evaluations "
                "(reference GameEstimatorEvaluationFunction.scala:141-146)"
            )
        return float(result.metrics[self.evaluation_suite.primary.name])

    # --- convertObservations ---

    def convert_observations(
        self, results: Sequence
    ) -> List[Tuple[np.ndarray, float]]:
        """Prior (explicit-grid) results → (vector, signed value) pairs;
        results without validation metrics are skipped."""
        out = []
        for r in results:
            if r.metrics is None:
                continue
            x = self.config_to_vector(r.config)
            out.append((x, self.direction * self._primary_metric(r)))
        return out
