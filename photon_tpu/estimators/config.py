"""Coordinate configuration model.

Parity targets: reference ``CoordinateDataConfiguration`` subclasses
(photon-api data/CoordinateDataConfiguration.scala:22-76),
``CoordinateOptimizationConfiguration`` + ``RegularizationContext``
(photon-api optimization/), and the client-side ``CoordinateConfiguration``
expansion of regularization-weight sets into per-weight optimization configs
(photon-client io/CoordinateConfiguration.scala).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from photon_tpu.optim.factory import OptimizerSpec
from photon_tpu.types import OptimizerType, VarianceComputationType


@dataclasses.dataclass(frozen=True)
class RegularizationConfig:
    """L1/L2/elastic-net weight split (reference RegularizationContext).

    ``alpha`` is the elastic-net mixing: l1 = alpha*weight,
    l2 = (1-alpha)*weight. alpha=0 → pure L2, alpha=1 → pure L1.
    """

    weight: float = 0.0
    alpha: float = 0.0

    @property
    def l1(self) -> float:
        return self.alpha * self.weight

    @property
    def l2(self) -> float:
        return (1.0 - self.alpha) * self.weight


@dataclasses.dataclass
class FixedEffectCoordinateConfig:
    coordinate_id: str
    feature_shard: str
    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iter: Optional[int] = None
    tol: Optional[float] = None
    reg_weights: Sequence[float] = (0.0,)
    reg_alpha: float = 0.0
    down_sampling_rate: Optional[float] = None
    # VarianceComputationType (or bool/str shorthand; True → SIMPLE)
    compute_variance: object = VarianceComputationType.NONE
    # Per-coordinate (lower, upper) bound vectors (data/constraints.py), fed
    # to the box-constrained solvers. GAME-side extension of the legacy
    # constraint map (GLMSuite.scala:49-126) — absent in the reference's
    # GAME path.
    box: Optional[tuple] = None

    def optimizer_spec(self) -> OptimizerSpec:
        return OptimizerSpec(self.optimizer, self.max_iter, self.tol, box=self.box)


@dataclasses.dataclass
class RandomEffectCoordinateConfig:
    coordinate_id: str
    re_type: str
    feature_shard: str
    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iter: Optional[int] = None
    tol: Optional[float] = None
    reg_weights: Sequence[float] = (0.0,)
    reg_alpha: float = 0.0
    active_upper_bound: Optional[int] = None
    active_lower_bound: Optional[int] = None
    features_to_samples_ratio: Optional[float] = None
    # VarianceComputationType (or bool/str shorthand; True → SIMPLE)
    compute_variance: object = VarianceComputationType.NONE
    # Convergence-gated active-set CD passes (algorithm/random_effect.py):
    # after the first full pass only entities whose coefficients still move
    # more than ``convergence_tol`` (relative) are re-solved; converged
    # entities keep their coefficients and scores. ``convergence_tol=None``
    # defers to the estimator-level default.
    active_set: bool = False
    convergence_tol: Optional[float] = None

    def optimizer_spec(self) -> OptimizerSpec:
        return OptimizerSpec(self.optimizer, self.max_iter, self.tol)


CoordinateConfig = object  # FixedEffectCoordinateConfig | RandomEffectCoordinateConfig


@dataclasses.dataclass(frozen=True)
class GameOptimizationConfig:
    """One point of the regularization-weight cross-product: coordinate id →
    regularization (prepareGameOptConfigs role, GameTrainingDriver.scala:632-641)."""

    reg: Dict[str, RegularizationConfig]

    def describe(self) -> str:
        return ", ".join(f"{k}: λ={v.weight:g} α={v.alpha:g}" for k, v in self.reg.items())


def expand_optimization_configs(
    configs: Sequence[CoordinateConfig],
) -> List[GameOptimizationConfig]:
    """Cross-product of per-coordinate reg-weight sets, ordered ascending per
    coordinate so warm starts move from strong to weak regularization like
    the reference's sweep (ModelTraining.scala:162-200 sorts weights)."""
    import itertools

    ids = [c.coordinate_id for c in configs]
    weight_lists = [sorted(c.reg_weights, reverse=True) for c in configs]
    alphas = {c.coordinate_id: c.reg_alpha for c in configs}
    out = []
    for combo in itertools.product(*weight_lists):
        out.append(
            GameOptimizationConfig(
                {
                    cid: RegularizationConfig(weight=w, alpha=alphas[cid])
                    for cid, w in zip(ids, combo)
                }
            )
        )
    return out
