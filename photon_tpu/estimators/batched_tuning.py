"""Batch-parallel hyperparameter evaluation: q GAME candidates as ONE
vmapped program.

The reference evaluates tuning candidates strictly sequentially — each
Bayesian round trains one full GAME model (GameEstimator.scala:364-382,
AtlasTuner loop). On a TPU the fixed-effect solves are HBM-bandwidth bound,
so q candidate trainings that differ only in regularization weights can
share every X pass: vmap the GLMix train step over traced per-lane λs
(``l2_override`` in margin-LBFGS / Newton) and evaluate all q validation
metrics inside the same program. SURVEY.md §2.7 item 5 names this the
natural TPU win over the reference.

Eligibility (falls back to sequential fits otherwise): one fixed-effect +
one random-effect coordinate (the GLMix shape), pure-L2 tuning dimensions,
unprojected entity blocks, no down-sampling/boxes/feature masks, and a
jittable primary metric. Normalization-folded shards ARE eligible (r4): the
per-shard fold is static per lane, and models convert between transformed
and model space exactly as the production coordinates do. Every fallback is
logged (VERDICT r3: a silent fallback makes "8 candidates per program"
quietly mean 1).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


def _decline(reason: str) -> None:
    logger.warning(
        "batched hyperparameter evaluation declined (%s); candidates will "
        "be trained sequentially", reason,
    )
    return None

from photon_tpu.estimators.config import (
    FixedEffectCoordinateConfig,
    GameOptimizationConfig,
    RandomEffectCoordinateConfig,
)

# Jittable primary metrics (evaluation/evaluators.py): name → fn(scores,
# labels, weight) -> scalar.
_JITTABLE_METRICS = ("AUC", "AUPR", "RMSE", "LOGISTIC_LOSS", "SQUARED_LOSS",
                    "POISSON_LOSS")


def _metric_fn(name: str):
    from photon_tpu.evaluation import evaluators as ev

    return {
        "AUC": ev.auc_roc,
        "AUPR": ev.auc_pr,
        "RMSE": ev.rmse,
        "LOGISTIC_LOSS": ev.logistic_loss_metric,
        "SQUARED_LOSS": ev.squared_loss_metric,
        "POISSON_LOSS": ev.poisson_loss_metric,
    }[name]


def build_batched_evaluator(
    estimator,
    base_config: GameOptimizationConfig,
    slots,  # GameEstimatorEvaluationFunction._slots (coordinate_id, kind)
    batch,
    validation_batch,
    evaluation_suite,
) -> Optional[Callable[[np.ndarray], List[float]]]:
    """Return fn(X: (q, dim) candidate vectors) -> list of q primary-metric
    values, or None when the setup is not batchable."""
    cfgs = estimator.coordinate_configs
    if len(cfgs) != 2:
        return _decline(
            f"{len(cfgs)} coordinates; only the 2-coordinate GLMix shape "
            "is batchable"
        )
    fe_cfgs = [c for c in cfgs if isinstance(c, FixedEffectCoordinateConfig)]
    re_cfgs = [c for c in cfgs if isinstance(c, RandomEffectCoordinateConfig)]
    if len(fe_cfgs) != 1 or len(re_cfgs) != 1:
        return _decline("need exactly one fixed + one random effect")
    fe_cfg, re_cfg = fe_cfgs[0], re_cfgs[0]
    if estimator.update_sequence[0] != fe_cfg.coordinate_id:
        return _decline("update sequence does not train the fixed effect first")
    # Tuning dims must be pure-L2 weights (l2_override hook).
    if any(kind != "weight" for _, kind in ((s.coordinate_id, s.kind) for s in slots)):
        return _decline("non-L2-weight tuning dimension")
    if any(base_config.reg[c.coordinate_id].alpha != 0.0 for c in cfgs):
        return _decline("elastic-net alpha != 0")
    if (
        fe_cfg.down_sampling_rate is not None
        or getattr(fe_cfg, "box", None) is not None
        or re_cfg.features_to_samples_ratio is not None
    ):
        return _decline("down-sampling / box constraints / Pearson masks")
    if estimator.locked_coordinates:
        return _decline("locked coordinates")
    primary = evaluation_suite.primary
    if primary.etype.name not in _JITTABLE_METRICS or primary.group_by is not None:
        return _decline(f"primary metric {primary.name} is not jittable")
    from photon_tpu.types import OptimizerType

    if fe_cfg.optimizer != OptimizerType.LBFGS:
        return _decline("fixed-effect optimizer is not LBFGS")
    if re_cfg.optimizer not in (OptimizerType.LBFGS, OptimizerType.NEWTON):
        return _decline("random-effect optimizer is not LBFGS/NEWTON")

    # Datasets: unprojected RE dataset (any block count).
    estimator._prepare_datasets(batch)
    ds = estimator._re_datasets.get(re_cfg.coordinate_id)
    if ds is None or ds.projected:
        return _decline("projected random-effect dataset")

    import jax
    import jax.numpy as jnp

    from photon_tpu.algorithm.random_effect import newton_eligible
    from photon_tpu.data.batch import LabeledBatch
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.ops.objective import GLMObjective
    from photon_tpu.optim.margin_lbfgs import minimize_lbfgs_margin
    from photon_tpu.optim.newton import minimize_newton

    loss = loss_for_task(estimator.task)
    fe_shard, re_shard = fe_cfg.feature_shard, re_cfg.feature_shard
    fe_icpt = estimator.intercept_indices.get(fe_shard)
    re_icpt = estimator.intercept_indices.get(re_shard)
    # Base λs: lanes override via l2_override, so the static weight only
    # matters for coordinates without a tuning slot. Normalization folds
    # exactly as in the production coordinates (_build_coordinates).
    fe_norm = estimator.normalization.get(fe_shard)
    re_norm = estimator.normalization.get(re_shard)
    fe_obj = GLMObjective(
        loss=loss, l2_weight=base_config.reg[fe_cfg.coordinate_id].l2,
        intercept_index=fe_icpt, normalization=fe_norm,
    )
    re_obj = GLMObjective(
        loss=loss, l2_weight=base_config.reg[re_cfg.coordinate_id].l2,
        intercept_index=re_icpt, normalization=re_norm,
    )
    fe_folded = fe_norm is not None and not fe_norm.is_identity
    re_folded = re_norm is not None and not re_norm.is_identity
    fe_spec_cfg = dataclasses.replace(
        fe_cfg.optimizer_spec().config(), track_history=False
    )
    re_spec_cfg = dataclasses.replace(
        re_cfg.optimizer_spec().config(), track_history=False
    )

    re_type = re_cfg.re_type
    train_lb = batch.labeled_batch(fe_shard)
    train_re_feats = batch.features[re_shard]
    train_eids = batch.entity_ids[re_type]
    valid_lb = validation_batch.labeled_batch(fe_shard)
    valid_re_feats = validation_batch.features[re_shard]
    valid_eids = validation_batch.entity_ids[re_type]
    E, d_fix = ds.num_entities, train_lb.dim
    d_re = ds.dim
    num_iterations = estimator.num_iterations
    metric = _metric_fn(primary.etype.name)

    # Slot → lane-λ extraction (log10-weight space).
    slot_for = {s.coordinate_id: i for i, s in enumerate(slots)}
    fe_slot = slot_for.get(fe_cfg.coordinate_id)
    re_slot = slot_for.get(re_cfg.coordinate_id)
    fe_base = base_config.reg[fe_cfg.coordinate_id].l2
    re_base = base_config.reg[re_cfg.coordinate_id].l2

    @jax.jit
    def eval_lanes(fe_lams, re_lams):  # (q,), (q,) traced λs
        def re_scores_of(coefs, feats, eids):
            ok = eids >= 0
            return jnp.where(
                ok, jnp.sum(feats * coefs[jnp.maximum(eids, 0)], -1), 0.0
            )

        def one(lf, lr):
            # The mini coordinate-descent loop of the production path
            # (CoordinateDescent → FE margin-LBFGS → per-block batched
            # Newton), parameterized by this lane's traced λs. Carries live
            # in MODEL space; solves convert in/out exactly like the
            # production coordinates.
            w = jnp.zeros((d_fix,), jnp.float32)
            coefs = jnp.zeros((E, d_re), jnp.float32)
            for _ in range(num_iterations):
                re_sc = re_scores_of(coefs, train_re_feats, train_eids)
                w_start = (
                    fe_norm.model_to_transformed_space(w) if fe_folded else w
                )
                fe_res = minimize_lbfgs_margin(
                    fe_obj, train_lb.add_scores_to_offsets(re_sc), w_start,
                    fe_spec_cfg, l2_override=lf,
                )
                w = (
                    fe_norm.transformed_to_model_space(fe_res.w)
                    if fe_folded else fe_res.w
                )
                fe_scores = train_lb.margins(w)  # includes base offsets
                for block in ds.blocks:
                    offs = block.gather_offsets(fe_scores)
                    w0 = coefs[block.entity_idx]
                    # Same static routing predicate as the production
                    # _solve_block (ADVICE r3: an explicit NEWTON spec past
                    # the auto-dim cap must not score with a different
                    # solver than the final refit).
                    use_newton = newton_eligible(
                        re_obj, re_cfg.optimizer_spec(), block.dim,
                        has_mask=False,
                    )

                    def solve_one(feat, lab, wt, off, wi):
                        lb = LabeledBatch(lab, feat, off, wt)
                        wi_t = (
                            re_norm.model_to_transformed_space(wi)
                            if re_folded else wi
                        )
                        if use_newton:
                            res = minimize_newton(
                                re_obj, lb, wi_t, re_spec_cfg, l2_override=lr
                            )
                        else:
                            res = minimize_lbfgs_margin(
                                re_obj, lb, wi_t, re_spec_cfg, l2_override=lr
                            )
                        return (
                            re_norm.transformed_to_model_space(res.w)
                            if re_folded else res.w
                        )

                    w_new = jax.vmap(solve_one)(
                        block.features, block.label, block.weight, offs, w0
                    )
                    w_new = jnp.where(block.train_mask[:, None], w_new, w0)
                    coefs = coefs.at[block.entity_idx].set(w_new)
            re_scores = re_scores_of(coefs, valid_re_feats, valid_eids)
            val_scores = valid_lb.margins(w) + re_scores
            return metric(val_scores, valid_lb.label, valid_lb.weight)

        return jax.vmap(one)(fe_lams, re_lams)

    def evaluate(X: np.ndarray) -> List[float]:
        X = np.asarray(X, float)
        q = X.shape[0]
        fe_lams = (
            10.0 ** X[:, fe_slot] if fe_slot is not None
            else np.full(q, fe_base)
        )
        re_lams = (
            10.0 ** X[:, re_slot] if re_slot is not None
            else np.full(q, re_base)
        )
        vals = eval_lanes(
            jnp.asarray(fe_lams, jnp.float32), jnp.asarray(re_lams, jnp.float32)
        )
        return [float(v) for v in np.asarray(vals)]

    return evaluate
