from photon_tpu.estimators.config import (  # noqa: F401
    FixedEffectCoordinateConfig,
    GameOptimizationConfig,
    RandomEffectCoordinateConfig,
)
from photon_tpu.estimators.game_estimator import GameEstimator, GameResult  # noqa: F401
from photon_tpu.estimators.game_transformer import GameTransformer  # noqa: F401
