"""GameTransformer: batch scoring with a trained GameModel.

Parity target: reference ``GameTransformer`` (photon-api
transformers/GameTransformer.scala:39-318): load model → score a dataset →
optional evaluation; logValue of metrics.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax

from photon_tpu.data.game_data import GameBatch
from photon_tpu.evaluation.suite import EvaluationSuite
from photon_tpu.models.game import GameModel

Array = jax.Array
logger = logging.getLogger(__name__)


class GameTransformer:
    def __init__(self, model: GameModel, evaluation_suite: Optional[EvaluationSuite] = None):
        self.model = model
        self.evaluation_suite = evaluation_suite
        # Model passed as an argument so repeated transforms (same batch
        # shapes) reuse one compiled program instead of retracing against a
        # fresh model-closure every call.
        self._score = jax.jit(lambda model, batch: model.score_with_offset(batch))

    def transform(self, batch: GameBatch) -> Array:
        """Per-sample total scores (model + offsets), jitted."""
        scores = self._score(self.model, batch)
        if self.evaluation_suite is not None:
            metrics = self.evaluation_suite.evaluate_scores(scores, batch)
            logger.info("scoring evaluation: %s", metrics)
            self.last_metrics: Optional[Dict[str, float]] = metrics
        return scores
