"""GameTransformer: batch scoring with a trained GameModel.

Parity target: reference ``GameTransformer`` (photon-api
transformers/GameTransformer.scala:39-318): load model → score a dataset →
optional evaluation; logValue of metrics.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax

from photon_tpu.data.game_data import GameBatch
from photon_tpu.evaluation.suite import EvaluationSuite
from photon_tpu.models.game import GameModel

Array = jax.Array
logger = logging.getLogger(__name__)


class GameTransformer:
    def __init__(self, model: GameModel, evaluation_suite: Optional[EvaluationSuite] = None):
        self.model = model
        self.evaluation_suite = evaluation_suite
        # Model passed as an argument so repeated transforms (same batch
        # shapes) reuse one compiled program instead of retracing against a
        # fresh model-closure every call. trace_count increments inside the
        # traced body, so it counts REAL XLA traces (the retrace-contract
        # observable for streamed scoring: at most one per bucket shape),
        # not Python calls — the solve_cache.py counter pattern.
        self.trace_count = 0

        def _score(model, batch):
            self.trace_count += 1
            return model.score_with_offset(batch)

        self._score = jax.jit(_score)

    def transform(self, batch: GameBatch) -> Array:
        """Per-sample total scores (model + offsets), jitted."""
        scores = self._score(self.model, batch)
        if self.evaluation_suite is not None:
            metrics = self.evaluation_suite.evaluate_scores(scores, batch)
            logger.info("scoring evaluation: %s", metrics)
            self.last_metrics: Optional[Dict[str, float]] = metrics
        return scores
