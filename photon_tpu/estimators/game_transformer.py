"""GameTransformer: batch scoring with a trained GameModel.

Parity target: reference ``GameTransformer`` (photon-api
transformers/GameTransformer.scala:39-318): load model → score a dataset →
optional evaluation; logValue of metrics.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.game_data import GameBatch
from photon_tpu.evaluation.suite import EvaluationSuite
from photon_tpu.models.game import GameModel

Array = jax.Array
logger = logging.getLogger(__name__)


class GameTransformer:
    def __init__(self, model: GameModel, evaluation_suite: Optional[EvaluationSuite] = None):
        self.model = model
        self.evaluation_suite = evaluation_suite
        # Model passed as an argument so repeated transforms (same batch
        # shapes) reuse one compiled program instead of retracing against a
        # fresh model-closure every call. trace_count increments inside the
        # traced body, so it counts REAL XLA traces (the retrace-contract
        # observable for streamed scoring: at most one per bucket shape),
        # not Python calls — the solve_cache.py counter pattern.
        self.trace_count = 0

        def _score(model, batch):
            self.trace_count += 1
            return model.score_with_offset(batch)

        self._score = jax.jit(_score)

    def transform(self, batch: GameBatch, model: Optional[GameModel] = None) -> Array:
        """Per-sample total scores (model + offsets), jitted.

        ``model`` overrides the init-time model for this call — the serving
        engine passes its store's current ``scoring_model()`` so hot-table
        promotions take effect. Same pytree STRUCTURE as ``self.model`` →
        same compiled program (value-only swap, no retrace)."""
        scores = self._score(self.model if model is None else model, batch)
        if self.evaluation_suite is not None:
            metrics = self.evaluation_suite.evaluate_scores(scores, batch)
            logger.info("scoring evaluation: %s", metrics)
            self.last_metrics: Optional[Dict[str, float]] = metrics
        return scores

    def warm_up(self, template: GameBatch, row_buckets) -> int:
        """Compile the scorer for every row-count bucket an online caller
        will dispatch on, up front — the serving engine's startup step that
        turns "at most one trace per bucket" into "ZERO traces after
        warm-up" (compiles happen before traffic, never under a request).

        ``template`` is a 1-row batch with the production feature/entity
        layout; each bucket size pads it with inert rows (weight 0, entity
        -1 — data/padding.py) and scores it to completion. Tracing is
        shape-driven, so the dummy values never matter. Returns the number
        of fresh traces (== number of previously-unseen bucket shapes)."""
        import jax

        from photon_tpu.data.padding import pad_game_batch

        before = self.trace_count
        for n in sorted(set(int(b) for b in row_buckets)):
            padded = pad_game_batch(template, n, xp=jnp)
            jax.block_until_ready(self._score(self.model, padded))
        return self.trace_count - before
