"""GameEstimator: the fit() orchestrator.

Parity target: reference ``GameEstimator`` (photon-api
estimators/GameEstimator.scala:53-713): prepare per-coordinate datasets
(prepareTrainingDatasets:470-530), validation evaluators
(prepareValidationEvaluators:573-611), build coordinates via a factory
(CoordinateFactory role), loop over optimization configurations with warm
start (fit:310-404), run coordinate descent per configuration, return
(model, config, evaluation) triples for model selection.

TPU-first: datasets are built once (host-side grouping for random effects),
and the λ sweep re-uses them — only the objectives change; every training is
jit-compiled against the same shapes so the sweep hits the compile cache.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_tpu.algorithm.coordinate import Coordinate
from photon_tpu.algorithm.coordinate_descent import CoordinateDescent
from photon_tpu.algorithm.fixed_effect import FixedEffectCoordinate
from photon_tpu.algorithm.random_effect import RandomEffectCoordinate
from photon_tpu.data.game_data import GameBatch
from photon_tpu.data.normalization import NormalizationContext
from photon_tpu.data.random_effect import (
    RandomEffectDataConfig,
    build_random_effect_dataset,
)
from photon_tpu.estimators.config import (
    FixedEffectCoordinateConfig,
    GameOptimizationConfig,
    RandomEffectCoordinateConfig,
    expand_optimization_configs,
)
from photon_tpu.evaluation.suite import EvaluationSuite
from photon_tpu.models.game import (
    GameModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.ops.variance import normalize_variance_type
from photon_tpu.sampling.down_sampler import down_sampler_for_task
from photon_tpu.types import TaskType, VarianceComputationType
from photon_tpu.utils.timed import Timed

logger = logging.getLogger(__name__)

CoordinateConfig = Union[FixedEffectCoordinateConfig, RandomEffectCoordinateConfig]


def _existing_entity_mask(prev_model) -> np.ndarray:
    """(E,) bool — which entities the warm-start model has a record for.

    Presence means record membership (reference
    RandomEffectDataset.scala:550-570), never coefficient values: an
    all-zero L1-sparsified row is still an EXISTING model and must keep the
    active-data bound. The loader's ``present_entities`` mask is
    authoritative when set; a projected model's presence is entity_block ≥ 0
    (entities with no block never had data or a model); a dense in-memory
    model without the mask treats every row as existing.
    """
    pm = getattr(prev_model, "present_entities", None)
    if pm is not None:
        return np.asarray(pm, bool)
    if isinstance(prev_model, ProjectedRandomEffectModel):
        return np.asarray(prev_model.entity_block) >= 0
    if isinstance(prev_model, RandomEffectModel):
        return np.ones((prev_model.num_entities,), bool)
    raise TypeError(
        "warm-start model for a random-effect coordinate must be a "
        "RandomEffectModel or ProjectedRandomEffectModel, got "
        f"{type(prev_model).__name__}"
    )


@dataclasses.dataclass
class GameResult:
    """(model, config, evaluations) triple (reference fit() return)."""

    model: GameModel
    config: GameOptimizationConfig
    metrics: Optional[Dict[str, float]]
    tracker: Dict[str, list]
    # Host wall seconds per (coordinate, CD pass) — carried from
    # CoordinateDescentResult so the run report joins diagnostics with
    # timing without re-running anything.
    wall_times: Dict[str, List[float]] = dataclasses.field(default_factory=dict)


class GameEstimator:
    """Trains GAME models over a list of optimization configurations.

    Args:
      task: GLM task for every coordinate (reference trainingTask param).
      coordinate_configs: data+optimizer config per coordinate, in update-
        sequence order.
      num_iterations: coordinate-descent passes per configuration.
      intercept_indices: feature-shard -> intercept column (excluded from
        regularization).
      normalization: feature-shard -> NormalizationContext.
      num_entities: RE type -> entity count (for dataset building).
    """

    def __init__(
        self,
        task: TaskType,
        coordinate_configs: Sequence[CoordinateConfig],
        num_iterations: int = 1,
        intercept_indices: Optional[Dict[str, int]] = None,
        normalization: Optional[Dict[str, NormalizationContext]] = None,
        num_entities: Optional[Dict[str, int]] = None,
        locked_coordinates: Sequence[str] = (),
        variance_computation: object = None,  # VarianceComputationType/bool/str
        ignore_threshold_for_new_models: bool = False,
        warm_start_model=None,  # GameModel the flag reads existing ids from
        re_active_set: bool = False,
        re_convergence_tol: float = 1e-4,
        re_device_budget_mb: Optional[float] = None,
        re_spill_dir: Optional[str] = None,
        re_spill_member: Optional[str] = None,
    ):
        self.task = task
        self.coordinate_configs = list(coordinate_configs)
        self.num_iterations = num_iterations
        self.intercept_indices = intercept_indices or {}
        self.normalization = normalization or {}
        self.num_entities = num_entities or {}
        self.locked_coordinates = list(locked_coordinates)
        self.variance_computation = normalize_variance_type(variance_computation)
        # ignoreThresholdForNewModels (GameTrainingDriver.scala:169-172):
        # during warm start, entities WITHOUT an existing model bypass the
        # RE active-data lower bound. The reference validates this pairing
        # at driver start (validateParams, :250-252) — mirrored here at
        # construction so a mid-sweep tuning fit can never trip it.
        self.ignore_threshold_for_new_models = bool(ignore_threshold_for_new_models)
        self.warm_start_model = warm_start_model
        # Estimator-level active-set default (per-coordinate config wins,
        # same precedence shape as variance): convergence-gated random-
        # effect passes for every RE coordinate of this estimator.
        self.re_active_set = bool(re_active_set)
        self.re_convergence_tol = float(re_convergence_tol)
        # Out-of-core residency: device byte budget for every RE
        # coordinate's block data + in-flight coefficients (None → fully
        # resident). See algorithm/re_store.ReDeviceStore.
        self.re_device_budget_bytes = (
            int(re_device_budget_mb * (1 << 20))
            if re_device_budget_mb
            else None
        )
        self.re_spill_dir = re_spill_dir
        # Host-owned spill layout: when set, spill files land under
        # ``<re_spill_dir>/host-<k>/`` so a fleet rebalance moves files
        # instead of re-streaming rows (re_store.rebalance_spill_layout).
        self.re_spill_member = re_spill_member
        if self.ignore_threshold_for_new_models and warm_start_model is None:
            raise ValueError(
                "'Ignore threshold for new models' flag set but no initial "
                "model provided for warm-start"
            )
        self.update_sequence = [c.coordinate_id for c in self.coordinate_configs]

    def _variance_type(self, cfg):
        """Per-coordinate setting wins; estimator-level is the fallback
        (reference variance flag precedence)."""
        per = normalize_variance_type(cfg.compute_variance)
        return per if per != VarianceComputationType.NONE else self.variance_computation

    # --- prepareTrainingDatasets role ---

    def _build_coordinates(
        self, batch: GameBatch, opt_config: GameOptimizationConfig
    ) -> Dict[str, Coordinate]:
        coords: Dict[str, Coordinate] = {}
        loss = loss_for_task(self.task)
        for cfg in self.coordinate_configs:
            reg = opt_config.reg[cfg.coordinate_id]
            if isinstance(cfg, FixedEffectCoordinateConfig):
                objective = GLMObjective(
                    loss=loss,
                    l2_weight=reg.l2,
                    l1_weight=reg.l1,
                    intercept_index=self.intercept_indices.get(cfg.feature_shard),
                    normalization=self.normalization.get(cfg.feature_shard),
                )
                sampler = (
                    down_sampler_for_task(self.task, cfg.down_sampling_rate)
                    if cfg.down_sampling_rate is not None and cfg.down_sampling_rate < 1.0
                    else None
                )
                coords[cfg.coordinate_id] = FixedEffectCoordinate(
                    coordinate_id=cfg.coordinate_id,
                    feature_shard=cfg.feature_shard,
                    task=self.task,
                    objective=objective,
                    optimizer_spec=cfg.optimizer_spec(),
                    down_sampler=sampler,
                    compute_variance=self._variance_type(cfg),
                    dim=batch.features[cfg.feature_shard].shape[1],
                )
            elif isinstance(cfg, RandomEffectCoordinateConfig):
                ds = self._re_datasets[cfg.coordinate_id]
                objective = GLMObjective(
                    loss=loss,
                    l2_weight=reg.l2,
                    l1_weight=reg.l1,
                    intercept_index=self.intercept_indices.get(cfg.feature_shard),
                    # Same per-shard fold as the fixed effect (the reference
                    # passes NormalizationContexts per shard to every
                    # coordinate via CoordinateFactory).
                    normalization=self.normalization.get(cfg.feature_shard),
                )
                coords[cfg.coordinate_id] = RandomEffectCoordinate(
                    coordinate_id=cfg.coordinate_id,
                    dataset=ds,
                    task=self.task,
                    objective=objective,
                    optimizer_spec=cfg.optimizer_spec(),
                    compute_variance=self._variance_type(cfg),
                    active_set=bool(cfg.active_set or self.re_active_set),
                    convergence_tol=(
                        cfg.convergence_tol
                        if cfg.convergence_tol is not None
                        else self.re_convergence_tol
                    ),
                    device_budget_bytes=self.re_device_budget_bytes,
                    device_spill_dir=self.re_spill_dir,
                    device_spill_member=self.re_spill_member,
                )
            else:
                raise TypeError(f"unknown coordinate config {type(cfg)}")
        return coords

    def _prepare_datasets(self, batch: GameBatch) -> None:
        """Random-effect grouping happens once per fit() — the λ sweep
        reuses the blocks (the reference rebuilds per config; we don't).
        Repeated fits on the SAME batch (hyperparameter tuning calls fit
        once per candidate) reuse the previous grouping."""
        if getattr(self, "_prepared_for", None) is batch:
            return
        self._re_datasets = {}
        from photon_tpu.data.batch import SparseFeatures

        # Sparse (wide) shards pass through as host triples — the builder
        # compacts each block to its active-column subspace instead of
        # densifying the full shard width.
        feats_np = {
            k: (
                (np.asarray(v.indices), np.asarray(v.values), v.dim)
                if isinstance(v, SparseFeatures)
                else np.asarray(v)
            )
            for k, v in batch.features.items()
        }
        label_np = np.asarray(batch.label)
        weight_np = np.asarray(batch.weight)
        for cfg in self.coordinate_configs:
            if isinstance(cfg, RandomEffectCoordinateConfig):
                eids = np.asarray(batch.entity_ids[cfg.re_type])
                E = self.num_entities.get(cfg.re_type, int(eids.max()) + 1 if eids.size else 0)
                existing = None
                if self.ignore_threshold_for_new_models:
                    # Entities with an existing model in the warm-start
                    # GameModel; ids outside it bypass the bound. Presence
                    # comes from the loader's record-membership mask when
                    # available (L1-zeroed models still count as existing,
                    # matching the reference's key-presence semantics);
                    # nonzero rows are the fallback for in-memory models.
                    existing = np.zeros((E,), bool)
                    prev_model = self.warm_start_model.get(cfg.coordinate_id)
                    if prev_model is not None:
                        existing_src = _existing_entity_mask(prev_model)
                        k = min(E, existing_src.shape[0])
                        existing[:k] = existing_src[:k]
                self._re_datasets[cfg.coordinate_id] = build_random_effect_dataset(
                    eids,
                    feats_np[cfg.feature_shard],
                    label_np,
                    weight_np,
                    E,
                    RandomEffectDataConfig(
                        re_type=cfg.re_type,
                        feature_shard=cfg.feature_shard,
                        active_upper_bound=cfg.active_upper_bound,
                        active_lower_bound=cfg.active_lower_bound,
                        features_to_samples_ratio=cfg.features_to_samples_ratio,
                    ),
                    uid=None if batch.uid is None else np.asarray(batch.uid),
                    existing_model_mask=existing,
                )
        self._prepared_for = batch

    # --- fit ---

    def fit(
        self,
        batch: GameBatch,
        validation_batch: Optional[GameBatch] = None,
        evaluation_suite: Optional[EvaluationSuite] = None,
        optimization_configs: Optional[Sequence[GameOptimizationConfig]] = None,
        initial_model: Optional[GameModel] = None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        checkpoint_keep_last: Optional[int] = None,
        emitter=None,  # utils.events.EventEmitter for optimization-log events
    ) -> List[GameResult]:
        """Train one GameModel per optimization configuration, warm-starting
        each config from the previous result (fit:364-382 role).

        With ``checkpoint_dir``, each config's coordinate descent checkpoints
        under ``<dir>/cfg_<i>`` and resumes from its latest state — an
        already-finished config replays from its final checkpoint without
        recomputation, so a preempted λ-sweep continues where it stopped."""
        with Timed("game-estimator/prepare-datasets"):
            self._prepare_datasets(batch)

        configs = (
            list(optimization_configs)
            if optimization_configs is not None
            else expand_optimization_configs(self.coordinate_configs)
        )
        validation_fn = better = None
        if evaluation_suite is not None and validation_batch is not None:
            validation_fn = evaluation_suite.validation_fn()
            better = evaluation_suite.primary.better()

        results: List[GameResult] = []
        warm = initial_model
        for cfg_idx, opt_config in enumerate(configs):
            with Timed(f"game-estimator/train[{opt_config.describe()}]"):
                coords = self._build_coordinates(batch, opt_config)
                cd = CoordinateDescent(
                    coords,
                    self.update_sequence,
                    num_iterations=self.num_iterations,
                    locked_coordinates=self.locked_coordinates,
                )
                cd_result = cd.run(
                    batch,
                    initial_model=warm,
                    validation_batch=validation_batch,
                    validation_fn=validation_fn,
                    better=better if better is not None else (lambda a, b: a < b),
                    checkpoint_dir=(
                        None
                        if checkpoint_dir is None
                        else f"{checkpoint_dir}/cfg_{cfg_idx}"
                    ),
                    checkpoint_every=checkpoint_every,
                    checkpoint_keep_last=checkpoint_keep_last,
                    # Fingerprint the λ-sweep point: resuming against a
                    # changed grid/sequence fails loudly instead of serving a
                    # stale model from the same cfg index.
                    checkpoint_tag=f"{opt_config.describe()}|{','.join(self.update_sequence)}",
                    emitter=emitter,
                )
            metrics = cd_result.metric_history[-1] if cd_result.metric_history else None
            results.append(
                GameResult(
                    model=cd_result.best_model,
                    config=opt_config,
                    metrics=metrics,
                    tracker=cd_result.tracker,
                    wall_times=cd_result.wall_times,
                )
            )
            warm = cd_result.model  # warm start the next λ point
            logger.info("trained config (%s): metrics=%s", opt_config.describe(), metrics)
        return results

    def select_best(
        self, results: List[GameResult], evaluation_suite: EvaluationSuite
    ) -> GameResult:
        """Best model by the primary validation metric (selectModels role,
        GameTrainingDriver.scala:701-766)."""
        primary = evaluation_suite.primary
        better = primary.better()
        best = None
        for r in results:
            if r.metrics is None:
                continue
            v = r.metrics[primary.name]
            if best is None or better(v, best.metrics[primary.name]):
                best = r
        return best if best is not None else results[-1]
