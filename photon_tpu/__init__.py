"""photon-tpu: a TPU-native framework for large-scale GLM and GLMix (GAME) training.

A from-scratch JAX/XLA re-design of the capabilities of LinkedIn's Photon ML
(reference: /root/reference, Spark/Scala). The compute path is jit/vmap/pjit over
a `jax.sharding.Mesh`; distributed gradient reductions are XLA collectives (psum)
instead of Spark treeAggregate; per-entity random-effect solves are vmapped
fixed-shape batched optimizations instead of RDD mapValues loops.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

- ``photon_tpu.ops``       — pointwise losses, objective functions, linalg (photon-lib function/)
- ``photon_tpu.optim``     — L-BFGS / OWL-QN / L-BFGS-B / TRON, trackers (photon-lib optimization/)
- ``photon_tpu.parallel``  — mesh construction, sharded objective wrappers (Spark treeAggregate role)
- ``photon_tpu.data``      — batches, index maps, stats, normalization, bucketing (photon-api data/)
- ``photon_tpu.models``    — Coefficients, GLMs, GameModel (photon-lib/api model/)
- ``photon_tpu.algorithm`` — coordinates + coordinate descent (photon-lib/api algorithm/)
- ``photon_tpu.evaluation``— AUC/RMSE/P@k evaluators (photon-lib/api evaluation/)
- ``photon_tpu.hyperparameter`` — Sobol + GP Bayesian search (photon-lib hyperparameter/)
- ``photon_tpu.io``        — Avro codec, model/data I/O (photon-client data/avro/)
- ``photon_tpu.estimators``— GameEstimator / GameTransformer (photon-api estimators/)
- ``photon_tpu.cli``       — training / scoring / indexing drivers (photon-client)
"""

__version__ = "0.1.0"

from photon_tpu.types import TaskType  # noqa: F401
