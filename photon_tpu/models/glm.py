"""Generalized linear models.

Parity target: reference photon-api supervised/model/GeneralizedLinearModel
.scala:33-156 and task wrappers (LogisticRegressionModel.scala:31,
LinearRegressionModel, PoissonRegressionModel, SmoothedHinge...). One class
parameterized by TaskType replaces the subclass-per-task hierarchy — the mean
function comes from the task's PointwiseLoss.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Features, LabeledBatch
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    coefficients: Coefficients
    task: TaskType = dataclasses.field(metadata=dict(static=True))

    def compute_score(self, features: Features) -> Array:
        """Raw margin x·w (GeneralizedLinearModel.computeScore,
        reference :61)."""
        return self.coefficients.compute_score(features)

    def compute_scores(self, batch: LabeledBatch) -> Array:
        """Margins including the batch offsets."""
        return self.compute_score(batch.features) + batch.offset

    def compute_mean(self, features: Features, offset: Optional[Array] = None) -> Array:
        """E[y|x]: the task's inverse link applied to the margin
        (computeMeanFunction in the reference subclasses)."""
        z = self.compute_score(features)
        if offset is not None:
            z = z + offset
        return loss_for_task(self.task).mean(z)

    def predict_class(self, features: Features, threshold: float = 0.5) -> Array:
        """Binary decision for classification tasks (BinaryClassifier role)."""
        if self.task == TaskType.LOGISTIC_REGRESSION:
            return (self.compute_mean(features) > threshold).astype(jnp.int32)
        if self.task == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
            return (self.compute_score(features) > 0).astype(jnp.int32)
        raise ValueError(f"{self.task} is not a classification task")

    @staticmethod
    def zeros(dim: int, task: TaskType, dtype=jnp.float32) -> "GeneralizedLinearModel":
        return GeneralizedLinearModel(Coefficients.zeros(dim, dtype), task)
