"""Model coefficients.

Parity target: reference photon-lib model/Coefficients.scala:31-49 —
``Coefficients(means, variancesOption)`` with ``computeScore`` dot product.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Features, SparseFeatures

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features: Features) -> Array:
        if isinstance(features, SparseFeatures):
            return features.matvec(self.means)
        return features @ self.means

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(jnp.zeros((dim,), dtype))
