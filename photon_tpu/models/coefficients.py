"""Model coefficients.

Parity target: reference photon-lib model/Coefficients.scala:31-49 —
``Coefficients(means, variancesOption)`` with ``computeScore`` dot product.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Features, SparseFeatures

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features: Features) -> Array:
        if isinstance(features, SparseFeatures):
            return features.matvec(self.means)
        # Broadcast-multiply + per-row reduce instead of ``features @ means``:
        # XLA CPU lowers the matvec to DIFFERENT accumulation orders at
        # different row counts (gemv at n=1, tiled gemm variants above), so
        # matmul scores are not bit-stable across batch sizes. The per-row
        # reduce is — which is what lets chunked/streamed/micro-batched
        # scoring promise atol=0 parity with the slurped batch path
        # (tests pin this; serving's bucket-padded dispatch relies on it).
        return jnp.sum(features * self.means, axis=-1)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(jnp.zeros((dim,), dtype))
