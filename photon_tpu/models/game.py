"""GAME model: fixed-effect + random-effect submodels.

Parity targets: reference photon-lib model/GameModel.scala:32-142 (scoring =
sum over sub-model scores), photon-api model/FixedEffectModel.scala:33-113
(broadcast GLM) and model/RandomEffectModel.scala:36-226 (RDD[(REId, GLM)],
score via join).

TPU-first design: a RandomEffectModel is ONE dense (E, d_shard) coefficient
matrix sharded over the mesh's entity axis; scoring is a gather by each
sample's entity index + a rowwise dot — the reference's model×data join is a
single XLA gather. Missing entities (index -1) score 0, mirroring the
reference's missing-submodel semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.data.game_data import GameBatch
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM over one feature shard (reference FixedEffectModel.scala).
    In SPMD there is no broadcast step: w is replicated by sharding rule."""

    model: GeneralizedLinearModel
    feature_shard: str = dataclasses.field(metadata=dict(static=True))

    def score(self, batch: GameBatch) -> Array:
        """Raw per-sample scores x·w (no offset — offsets/residuals are
        handled by the coordinate descent loop)."""
        return self.model.compute_score(batch.features[self.feature_shard])


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity GLMs as one dense coefficient matrix.

    coefficients: (E, d_shard); row e is entity e's model in the shard's
    feature space. variances: optional (E, d_shard).
    """

    coefficients: Array
    re_type: str = dataclasses.field(metadata=dict(static=True))
    feature_shard: str = dataclasses.field(metadata=dict(static=True))
    task: TaskType = dataclasses.field(metadata=dict(static=True))
    variances: Optional[Array] = None
    # (E,) bool — which entities had a persisted per-entity model record
    # (set by load_game_model). Distinguishes a legitimately all-zero
    # L1-sparsified model from an entity that was never trained — the
    # reference keys existing-model checks on record membership
    # (RandomEffectDataset.scala:550-570), not coefficient values.
    present_entities: Optional[Array] = None

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    def with_coefficients(self, coefficients: Array) -> "RandomEffectModel":
        """Serving copy: same static metadata, swapped coefficient table.
        The serving hot store passes an (H, d) device-resident hot table
        with H ≠ E and SLOT indices in ``batch.entity_ids`` — auxiliary
        arrays (variances, presence) are dropped so the scoring pytree
        structure is identical across models and reloads (one jit cache
        entry, never a retrace on swap)."""
        return RandomEffectModel(
            coefficients, self.re_type, self.feature_shard, self.task
        )

    def score(self, batch: GameBatch) -> Array:
        """Gather-by-entity scoring (replaces RandomEffectModel.scala's
        keyBy(REId).join(modelsRDD))."""
        idx = batch.entity_ids[self.re_type]
        valid = idx >= 0
        safe_idx = jnp.where(valid, idx, 0)
        w = self.coefficients[safe_idx]  # (n, d)
        feats = batch.features[self.feature_shard]
        if isinstance(feats, SparseFeatures):
            scores = jnp.sum(
                feats.values * jnp.take_along_axis(w, feats.indices, axis=1), axis=-1
            )
        else:
            scores = jnp.sum(feats * w, axis=-1)
        return jnp.where(valid, scores, 0.0)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProjectedRandomEffectModel:
    """Per-entity GLMs kept in per-block SUBSPACES (the wide-shard form).

    Role parity: reference RandomEffectModel + ModelProjection — per-entity
    models live in each entity's compact feature subspace and are projected
    back to the global space on demand (projector/LinearSubspaceProjector
    .scala:36-88, algorithm/ModelProjection.scala). Here the subspace is per
    vmap BLOCK (union of the block's active columns): coefficients are a
    list of (E_b, d_b) matrices + int32 column maps into the global space,
    so a shard of width d_full never materializes (E, d_full) HBM.

    entity_block/entity_row: (E_total,) int32 — which block (−1 = no data;
    scores 0) and which row within it holds each entity's model.
    inv_maps[b]: (d_full,) int32 — global column → block column (−1 absent).
    """

    block_coefs: list  # [(E_b, d_b)]
    col_maps: list  # [(d_b,) int32 global column ids]
    inv_maps: list  # [(d_full,) int32]
    entity_block: Array  # (E_total,)
    entity_row: Array  # (E_total,)
    d_full: int = dataclasses.field(metadata=dict(static=True))
    re_type: str = dataclasses.field(metadata=dict(static=True))
    feature_shard: str = dataclasses.field(metadata=dict(static=True))
    task: TaskType = dataclasses.field(metadata=dict(static=True))
    block_variances: Optional[list] = None

    @property
    def num_entities(self) -> int:
        return self.entity_block.shape[0]

    def score(self, batch: GameBatch) -> Array:
        """Gather-by-entity scoring without leaving block space: each
        sample's feature columns are translated through its entity's block
        inverse map; absent columns contribute 0 (the entity never saw that
        feature — its coefficient is implicitly 0)."""
        idx = batch.entity_ids[self.re_type]
        valid = idx >= 0
        safe = jnp.where(valid, idx, 0)
        blk = self.entity_block[safe]  # (n,)
        row = self.entity_row[safe]
        feats = batch.features[self.feature_shard]
        total = jnp.zeros((idx.shape[0],), jnp.float32)
        for b, (coefs, inv) in enumerate(zip(self.block_coefs, self.inv_maps)):
            in_b = valid & (blk == b)
            row_b = jnp.where(in_b, row, 0)
            w = coefs[row_b]  # (n, d_b)
            if isinstance(feats, SparseFeatures):
                loc = inv[feats.indices]  # (n, k) block-local columns
                gathered = jnp.take_along_axis(w, jnp.maximum(loc, 0), axis=1)
                s = jnp.sum(
                    jnp.where(loc >= 0, feats.values * gathered, 0.0), axis=-1
                )
            else:
                s = jnp.einsum(
                    "nd,nd->n", feats[:, self.col_maps[b]].astype(w.dtype), w
                )
            total = total + jnp.where(in_b, s, 0.0)
        return total

    def to_dense(self) -> RandomEffectModel:
        """Materialize the global-space (E, d_full) model (small shards,
        tests, interoperability). The wide-shard I/O path iterates blocks
        directly instead (io/model_io.py)."""
        E = self.num_entities
        coefs = jnp.zeros((E, self.d_full), jnp.float32)
        variances = None
        for b, (wb, cmap) in enumerate(zip(self.block_coefs, self.col_maps)):
            # A shape-bucketed block may hold fewer than E_b real entities:
            # fill the overflow with out-of-range row E and drop it at the
            # scatter (fill 0 would silently clobber entity 0's model).
            rows = jnp.flatnonzero(
                self.entity_block == b, size=wb.shape[0], fill_value=E
            )
            coefs = coefs.at[rows[:, None], cmap[None, :]].set(
                wb[self.entity_row[jnp.minimum(rows, E - 1)]], mode="drop"
            )
        if self.block_variances is not None:
            variances = jnp.ones((E, self.d_full), jnp.float32)
            for b, (vb, cmap) in enumerate(
                zip(self.block_variances, self.col_maps)
            ):
                rows = jnp.flatnonzero(
                    self.entity_block == b, size=vb.shape[0], fill_value=E
                )
                variances = variances.at[rows[:, None], cmap[None, :]].set(
                    vb[self.entity_row[jnp.minimum(rows, E - 1)]], mode="drop"
                )
        return RandomEffectModel(
            coefs, self.re_type, self.feature_shard, self.task, variances
        )


DatumScoringModel = Union[FixedEffectModel, RandomEffectModel, ProjectedRandomEffectModel]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GameModel:
    """Map coordinate-id -> submodel; total score = Σ submodel scores
    (GameModel.scoreForCoordinateDescent, reference GameModel.scala:102)."""

    models: Dict[str, DatumScoringModel]

    def score(self, batch: GameBatch) -> Array:
        total = jnp.zeros((batch.n,), batch.offset.dtype)
        for model in self.models.values():
            total = total + model.score(batch)
        return total

    def score_with_offset(self, batch: GameBatch) -> Array:
        return self.score(batch) + batch.offset

    def get(self, coordinate_id: str) -> Optional[DatumScoringModel]:
        return self.models.get(coordinate_id)

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(new)

    def updated_many(
        self, replacements: Dict[str, DatumScoringModel]
    ) -> "GameModel":
        """One-shot multi-coordinate swap (the serving store replaces every
        random-effect table atomically)."""
        new = dict(self.models)
        new.update(replacements)
        return GameModel(new)

    def feature_shard_dims(self) -> Dict[str, int]:
        """Feature dimensionality per shard, from the submodels themselves —
        what a serving front end needs to assemble request rows without the
        training dataset in hand. Coordinates sharing a shard agree by
        construction (they were trained on the same shard matrices)."""
        dims: Dict[str, int] = {}
        for sub in self.models.values():
            if isinstance(sub, FixedEffectModel):
                d = int(sub.model.coefficients.dim)
            elif isinstance(sub, RandomEffectModel):
                d = int(sub.coefficients.shape[1])
            else:
                d = int(sub.d_full)
            prev = dims.setdefault(sub.feature_shard, d)
            if prev != d:
                raise ValueError(
                    f"shard {sub.feature_shard!r} has inconsistent dims "
                    f"{prev} vs {d} across coordinates"
                )
        return dims
