"""GAME model: fixed-effect + random-effect submodels.

Parity targets: reference photon-lib model/GameModel.scala:32-142 (scoring =
sum over sub-model scores), photon-api model/FixedEffectModel.scala:33-113
(broadcast GLM) and model/RandomEffectModel.scala:36-226 (RDD[(REId, GLM)],
score via join).

TPU-first design: a RandomEffectModel is ONE dense (E, d_shard) coefficient
matrix sharded over the mesh's entity axis; scoring is a gather by each
sample's entity index + a rowwise dot — the reference's model×data join is a
single XLA gather. Missing entities (index -1) score 0, mirroring the
reference's missing-submodel semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.data.game_data import GameBatch
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM over one feature shard (reference FixedEffectModel.scala).
    In SPMD there is no broadcast step: w is replicated by sharding rule."""

    model: GeneralizedLinearModel
    feature_shard: str = dataclasses.field(metadata=dict(static=True))

    def score(self, batch: GameBatch) -> Array:
        """Raw per-sample scores x·w (no offset — offsets/residuals are
        handled by the coordinate descent loop)."""
        return self.model.compute_score(batch.features[self.feature_shard])


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity GLMs as one dense coefficient matrix.

    coefficients: (E, d_shard); row e is entity e's model in the shard's
    feature space. variances: optional (E, d_shard).
    """

    coefficients: Array
    re_type: str = dataclasses.field(metadata=dict(static=True))
    feature_shard: str = dataclasses.field(metadata=dict(static=True))
    task: TaskType = dataclasses.field(metadata=dict(static=True))
    variances: Optional[Array] = None

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    def score(self, batch: GameBatch) -> Array:
        """Gather-by-entity scoring (replaces RandomEffectModel.scala's
        keyBy(REId).join(modelsRDD))."""
        idx = batch.entity_ids[self.re_type]
        valid = idx >= 0
        safe_idx = jnp.where(valid, idx, 0)
        w = self.coefficients[safe_idx]  # (n, d)
        feats = batch.features[self.feature_shard]
        if isinstance(feats, SparseFeatures):
            scores = jnp.sum(
                feats.values * jnp.take_along_axis(w, feats.indices, axis=1), axis=-1
            )
        else:
            scores = jnp.sum(feats * w, axis=-1)
        return jnp.where(valid, scores, 0.0)


DatumScoringModel = Union[FixedEffectModel, RandomEffectModel]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GameModel:
    """Map coordinate-id -> submodel; total score = Σ submodel scores
    (GameModel.scoreForCoordinateDescent, reference GameModel.scala:102)."""

    models: Dict[str, DatumScoringModel]

    def score(self, batch: GameBatch) -> Array:
        total = jnp.zeros((batch.n,), batch.offset.dtype)
        for model in self.models.values():
            total = total + model.score(batch)
        return total

    def score_with_offset(self, batch: GameBatch) -> Array:
        return self.score(batch) + batch.offset

    def get(self, coordinate_id: str) -> Optional[DatumScoringModel]:
        return self.models.get(coordinate_id)

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(new)
