from photon_tpu.models.coefficients import Coefficients  # noqa: F401
from photon_tpu.models.glm import GeneralizedLinearModel  # noqa: F401
from photon_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel  # noqa: F401
