"""Core enums and type aliases.

Parity targets: TaskType (reference photon-lib TaskType.scala), type aliases
(reference photon-lib Types.scala:15-45), ConvergenceReason (reference
photon-lib util/ConvergenceReason.scala), NormalizationType (reference
photon-lib normalization/NormalizationType.scala:20).
"""

from __future__ import annotations

import enum

# Type aliases (reference Types.scala): UniqueSampleId = Long, CoordinateId /
# REType / REId / FeatureShardId = String. In the TPU design, sample ids and
# entity ids are int64 array indices — alignment by construction replaces joins.
UniqueSampleId = int
CoordinateId = str
FeatureShardId = str
REType = str


class TaskType(enum.Enum):
    """Supported GLM training tasks (reference TaskType.scala)."""

    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"


class ConvergenceReason(enum.Enum):
    """Why an optimizer stopped (reference util/ConvergenceReason.scala,
    Optimizer.getConvergenceReason Optimizer.scala:126-139)."""

    MAX_ITERATIONS = "MAX_ITERATIONS"
    FUNCTION_VALUES_CONVERGED = "FUNCTION_VALUES_CONVERGED"
    GRADIENT_CONVERGED = "GRADIENT_CONVERGED"
    OBJECTIVE_NOT_IMPROVING = "OBJECTIVE_NOT_IMPROVING"
    NOT_CONVERGED = "NOT_CONVERGED"
    # Not in the reference: the solve hit a non-finite iterate and was rolled
    # back to the last finite point (divergence guard, utils/faults.py story).
    DIVERGED = "DIVERGED"


class NormalizationType(enum.Enum):
    """Feature normalization schemes (reference NormalizationType.scala:20)."""

    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


class VarianceComputationType(enum.Enum):
    """Coefficient-variance computation mode (reference
    DistributedOptimizationProblem.scala:83-103: SIMPLE = inverse diagonal
    Hessian, FULL = diagonal of the full inverse Hessian via Cholesky)."""

    NONE = "NONE"
    SIMPLE = "SIMPLE"
    FULL = "FULL"


class OptimizerType(enum.Enum):
    """Optimizer selection (reference OptimizerType / OptimizerFactory)."""

    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    LBFGSB = "LBFGSB"
    TRON = "TRON"
    # TPU-native addition (no reference analogue): batched damped Newton with
    # exact (d, d) Cholesky solves — the natural second-order method for
    # vmapped small-dimension random-effect solves (optim/newton.py).
    NEWTON = "NEWTON"
