"""Pallas TPU kernel: fused GLM objective value + gradient in one pass over X.

Role parity: the reference's aggregator hot loop — per-sample dot product +
axpy accumulated across the cluster (ValueAndGradientAggregator.add/merge,
photon-lib aggregators/ValueAndGradientAggregator.scala:242-285). On TPU the
same computation as XLA emits it is TWO passes over X in HBM per objective
evaluation: one for ``z = X @ w`` and one for ``grad = Xᵀ · dz`` (the
transpose blocks fusion). Since the fixed-effect solve is HBM-bandwidth
bound (SURVEY.md §6 cost model: one such evaluation per L-BFGS line-search
point), halving X traffic halves the step time.

This kernel streams row-tiles of X through VMEM once per evaluation:

    per tile:  z  = X_tile @ w + offset          (MXU)
               lv = weight · loss(z, y)          (VPU, fused)
               dz = weight · loss'(z, y)         (VPU, fused)
               loss_acc += Σ lv                  (SMEM scalar)
               grad_acc += X_tileᵀ @ dz          (MXU, VMEM accumulator)

Grid steps on TPU are sequential per core, so accumulating into the same
output block across steps is race-free (standard reduction pattern). The
feature dimension is kept whole per tile (w and one (TILE_N, d) tile must
fit VMEM) — beyond that, the replicated path or the feature-sharded
shard_map path (photon_tpu.parallel.feature_sharded) applies.

L2/normalization are folded by the wrapper (effective-coefficient algebra,
photon_tpu.data.normalization), keeping the kernel a pure data-loss pass.

Round-4 FE bandwidth verdict (bench ``--fe-bandwidth-ab``, BENCH_FULL.md):
this file now holds exactly ONE lowering per entry point. The three
round-4 candidates all survive as PARTS of it — tall rebalanced tiles
(``_tile_geometry``), the fused one-pass HVP (``_hvp_kernel``), and the
explicit sequential-grid declaration (``_SEQUENTIAL_GRID``, a correctness
requirement on megacore parts, not a tunable) — while the losing
alternatives were deleted rather than gated: the short-tile per-call
``tile_n`` override is gone from both public signatures, and the
linearize/transpose HVP in ops/objective.py remains only as the
ineligibility fallback (sparse/wide/sharded), never a competing lowering
for fuse-eligible batches. On-chip confirmation is pending the TPU tunnel
(every number so far is CPU: interpret-mode parity + modeled traffic).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Import gate: pallas is an experimental surface that some CPU-only jax
# installs ship without (and whose API names move between releases).
# Importing THIS module must never break a training process that isn't
# using the fused path — record the failure and let the predicates below
# report it instead.
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORT_ERROR: Optional[BaseException] = None
except Exception as _exc:  # pragma: no cover - depends on jax build
    pl = None  # type: ignore[assignment]
    pltpu = None  # type: ignore[assignment]
    _PALLAS_IMPORT_ERROR = _exc

from photon_tpu.ops.losses import PointwiseLoss

Array = jax.Array

# Both kernels ACCUMULATE into their output block across grid steps, which
# requires the row-tile grid to run sequentially. Mosaic infers that from
# the constant output index map, but megacore parts (v4/v5p) split
# "parallel" grid dims across cores — declare the semantics explicitly so
# the reduction stays correct everywhere, not just on single-core v5e.
# (jax renamed TPUCompilerParams → CompilerParams across releases; accept
# whichever this build ships.)
_COMPILER_PARAMS_CLS = (
    None
    if pltpu is None
    else getattr(pltpu, "CompilerParams", None)
    or getattr(pltpu, "TPUCompilerParams", None)
)
_SEQUENTIAL_GRID = (
    _COMPILER_PARAMS_CLS(dimension_semantics=("arbitrary",))
    if _COMPILER_PARAMS_CLS is not None
    else None
)


def pallas_usable() -> bool:
    """True when the fused kernels can EXECUTE in this process — compiled
    on a TPU backend, or interpreted elsewhere (the CPU test path). False
    only when the pallas import itself failed."""
    return _PALLAS_IMPORT_ERROR is None


def pallas_available() -> bool:
    """True when the fused kernels can COMPILE and run at full speed: the
    pallas TPU surface imported, Mosaic compiler params resolved, and the
    default backend is a TPU. Off-TPU the kernels still run in interpreter
    mode (orders slower) — production call sites gate on this; tests opt
    into ``interpret=True`` explicitly."""
    return (
        _PALLAS_IMPORT_ERROR is None
        and _SEQUENTIAL_GRID is not None
        and jax.default_backend() == "tpu"
    )


def _require_pallas() -> None:
    if _PALLAS_IMPORT_ERROR is not None:
        raise RuntimeError(
            "pallas is unavailable in this jax build "
            f"({_PALLAS_IMPORT_ERROR!r}); the fused GLM kernels cannot run "
            "— strip use_pallas or install a jax with pallas support"
        )

# Requested row-tile height; the VMEM budget below is the real constraint
# (tile_cap), so this just needs to be "large". Grid steps run sequentially
# and carry fixed per-step cost (DMA semaphores, loop bookkeeping) — with
# 512-row tiles on the n=2^21, d=256 headline that cost dominated: 4096
# steps × ~1 µs ≈ 4 ms against a 1.25 ms pure-streaming pass, measured as
# FE traffic stuck at ~5% of HBM peak (BENCH_r02). Big tiles amortize it:
# at d=256/bf16 the budget admits 8192-row tiles = 256 steps.
DEFAULT_TILE_N = 8192
# Feature dims above this exceed the VMEM tile budget; callers fall back.
MAX_FUSED_DIM = 4096


def _kernel(loss: PointwiseLoss, w_ref, x_ref, y_ref, off_ref, wt_ref,
            loss_ref, grad_ref, z_ref=None):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]
    # All values kept rank-2 (Mosaic-friendly layouts; scalar/1-D reductions
    # with accumulation fail to lower — "Offset change").
    z = jnp.dot(x, w_ref[:], preferred_element_type=jnp.float32) + off_ref[:]
    if z_ref is not None:
        # Fresh margins out — lets margin-space solvers refresh their carried
        # margins exactly (no incremental z += α·u drift) at no extra X pass.
        z_ref[:] = z
    y = y_ref[:]
    wt = wt_ref[:]

    lv = wt * loss.value(z, y)
    dz = wt * loss.dz(z, y)

    # Per-tile loss partial (summed by the wrapper; avoids cross-step scalar
    # accumulation in SMEM, which Mosaic can't lower). The (tile_n,1)→(1,1)
    # reduce rides the MXU as a dot with ones.
    ones = jnp.ones((lv.shape[0], 1), jnp.float32)
    tile_sum = jax.lax.dot_general(
        lv, ones,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    loss_ref[pl.ds(i, 1), :] = tile_sum
    # Xᵀ · dz, contracting over the row (sample) axis: (d, 1), accumulated
    # across sequential grid steps.
    grad_ref[:] += jax.lax.dot_general(
        x, dz,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _hvp_kernel(v_ref, x_ref, d2_ref, out_ref):
    """One-pass GLM data-Hessian product: per row tile,
    u = X_tile·v (MXU), then out += X_tileᵀ·(d2 ∘ u) (MXU) — the tile is
    read from HBM once for both dots. d2 = weight·loss''(z, y) is
    precomputed by the caller at the current outer iterate."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]
    u = jnp.dot(x, v_ref[:], preferred_element_type=jnp.float32)
    t = d2_ref[:] * u
    out_ref[:] += jax.lax.dot_general(
        x, t,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def fused_data_hvp(
    v: Array,
    X: Array,
    d2: Array,
    interpret: Optional[bool] = None,
) -> Array:
    """Xᵀ·diag(d2)·X·v in ONE pass over ``X`` (vs two XLA passes for the
    forward and transpose matvecs). The data term of a GLM Hessian-vector
    product at fixed margins; pairs with GLMObjective.linearized_hvp,
    which caches d2 once per outer iteration
    (HessianVectorAggregator.scala role). Padding is exact (zero rows /
    columns contribute nothing).

    Tile geometry is fixed by ``DEFAULT_TILE_N`` (module constant, read at
    call time) — the round-4 FE bandwidth A/B kept the fused one-pass HVP
    as the only HVP lowering and retired the per-call tile-height override
    with the losing short-tile variants (BENCH_FULL.md, bench
    ``--fe-bandwidth-ab``). Tests vary geometry by monkeypatching
    ``pallas_glm.DEFAULT_TILE_N``.
    """
    _require_pallas()
    n, d = X.shape
    _check_fused_width(d, "fused_data_hvp")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d_pad = int(np.ceil(max(d, 1) / 128) * 128)
    tile_n, n_pad = _tile_geometry(n, d_pad, X.dtype, DEFAULT_TILE_N)
    if n_pad != n or d_pad != d:
        X = jnp.pad(X, ((0, n_pad - n), (0, d_pad - d)))
        d2 = jnp.pad(d2, (0, n_pad - n))
        v = jnp.pad(v, (0, d_pad - d))
    v2 = v.astype(X.dtype)[:, None]
    d2c = d2.astype(jnp.float32)[:, None]
    n_tiles = n_pad // tile_n
    out = pl.pallas_call(
        _hvp_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((d_pad, 1), lambda i: (0, 0)),       # v
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0)),  # X row tile
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),      # d2
        ],
        out_specs=pl.BlockSpec((d_pad, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad, 1), jnp.float32),
        compiler_params=None if interpret else _SEQUENTIAL_GRID,
        interpret=interpret,
    )(v2, X, d2c)
    hv = out[:, 0]
    return hv[:d] if d_pad != d else hv


def _tile_geometry(n: int, d_pad: int, dtype, tile_n: int) -> Tuple[int, int]:
    """Choose (tile_n, n_pad) for an (n, d_pad) matrix of ``dtype``.

    Constraints, in order: the X tile fits a fixed VMEM budget (Pallas
    double-buffers grid inputs, so effective footprint is ~2×); the tile is
    never taller than the data; and tile heights are REBALANCED across the
    resulting grid so padding never exceeds one sublane row per tile — a
    tall default must not round n=8200 up to two full 8192 tiles (that
    would nearly double the HBM traffic this kernel exists to minimize).
    """
    sublane = 16 if dtype == jnp.bfloat16 else 8
    budget = 4 * 1024 * 1024
    tile_cap = budget // (d_pad * jnp.dtype(dtype).itemsize)
    n_cap = int(np.ceil(max(n, 1) / sublane) * sublane)
    tile_n = max(sublane, min(tile_n, (tile_cap // sublane) * sublane, n_cap))
    # Rebalance: same tile count, evenly-sized tiles.
    n_tiles = int(np.ceil(max(n, 1) / tile_n))
    tile_n = int(np.ceil(np.ceil(max(n, 1) / n_tiles) / sublane) * sublane)
    n_pad = n_tiles * tile_n
    return tile_n, n_pad


def _check_fused_width(d: int, fn_name: str) -> None:
    """Every in-tree caller is gated by GLMObjective._can_fuse; a direct
    caller above the width limit would get a tile clamped to sublane rows,
    blow the 4 MB VMEM budget, and die in Mosaic with an opaque compile
    error (ADVICE r4). Fail fast and descriptively instead."""
    if d > MAX_FUSED_DIM:
        raise ValueError(
            f"{fn_name} supports d <= {MAX_FUSED_DIM} (got d={d}); "
            "use the two-pass XLA path for wider problems"
        )


def fused_data_value_and_grad(
    loss: PointwiseLoss,
    w: Array,
    X: Array,
    label: Array,
    offset: Array,
    weight: Array,
    interpret: Optional[bool] = None,
    return_margins: bool = False,
) -> Tuple[Array, ...]:
    """Σᵢ wᵢ·loss(xᵢ·w + offsetᵢ, yᵢ) and its gradient w.r.t. ``w``, in one
    pass over ``X``. Pure data term — no regularization, no normalization.

    Pads rows to the tile height with weight-0 samples and features to the
    lane width; both paddings are exact (zero contribution).
    ``interpret=None`` auto-selects interpreter mode off-TPU (CPU tests).

    ``X`` may be bfloat16 (half the HBM traffic of the bandwidth-bound read);
    margins and all accumulation stay float32 via preferred_element_type.

    With ``return_margins=True`` also returns the fresh margins
    ``z = X·w + offset`` (float32, shape (n,)) computed in the same pass —
    the margin-space L-BFGS uses this to refresh its carried margins exactly
    every iteration instead of accumulating ``z += α·u`` rounding drift.

    Tile geometry is fixed by ``DEFAULT_TILE_N`` (module constant, read at
    call time): the round-4 FE bandwidth A/B (bench ``--fe-bandwidth-ab``,
    BENCH_FULL.md) settled on tall rebalanced tiles under a sequential
    grid as the single surviving lowering, so the per-call tile-height
    override was deleted with the losing candidates. Tests vary geometry
    by monkeypatching ``pallas_glm.DEFAULT_TILE_N``.
    """
    _require_pallas()
    n, d = X.shape
    _check_fused_width(d, "fused_data_value_and_grad")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    d_pad = int(np.ceil(max(d, 1) / 128) * 128)
    tile_n, n_pad = _tile_geometry(n, d_pad, X.dtype, DEFAULT_TILE_N)
    if n_pad != n or d_pad != d:
        X = jnp.pad(X, ((0, n_pad - n), (0, d_pad - d)))
        label = jnp.pad(label, (0, n_pad - n))
        offset = jnp.pad(offset, (0, n_pad - n))
        weight = jnp.pad(weight, (0, n_pad - n))  # 0-weight padding rows
        w = jnp.pad(w, (0, d_pad - d))

    # w must match X's dtype — Mosaic stalls lowering mixed-dtype dots. With
    # bf16 X the margin matmul runs bf16×bf16 → f32 (preferred_element_type);
    # value/grad accumulation is f32 either way.
    w2 = w.astype(X.dtype)[:, None]
    col = lambda v: v.astype(jnp.float32)[:, None]

    n_tiles = n_pad // tile_n
    out_specs = [
        # Full-array resident block; each step stores its own row.
        pl.BlockSpec((n_tiles, 1), lambda i: (0, 0)),
        pl.BlockSpec((d_pad, 1), lambda i: (0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_tiles, 1), jnp.float32),
        jax.ShapeDtypeStruct((d_pad, 1), jnp.float32),
    ]
    if return_margins:
        out_specs.append(pl.BlockSpec((tile_n, 1), lambda i: (i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((n_pad, 1), jnp.float32))

    outs = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((d_pad, 1), lambda i: (0, 0)),           # w
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0)),      # X row tile
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),          # y
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),          # offset
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),          # weight
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=None if interpret else _SEQUENTIAL_GRID,
        interpret=interpret,
    )(w2, X, col(label), col(offset), col(weight))

    loss_out, grad_out = outs[0], outs[1]
    value = jnp.sum(loss_out)
    grad = grad_out[:, 0]
    if d_pad != d:
        grad = grad[:d]
    if return_margins:
        return value, grad, outs[2][:n, 0]
    return value, grad
