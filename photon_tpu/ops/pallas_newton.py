"""Batched small-GLM Newton-system Pallas kernel for random effects.

Role parity: the reference solves thousands of tiny per-entity GLMs inside
``mapValues`` (photon-api algorithm/RandomEffectCoordinate.scala:228-283) —
one Breeze optimizer per entity on whatever executor holds the partition.
The TPU rebuild already collapses a bucket of entities into ONE vmapped
damped-Newton program (optim/newton.py); this module collapses that
program's X-touching work into a single Pallas kernel with **one grid
instance per bucketed block row**: each instance streams its entity's
(n_max, d) feature slab through VMEM once and assembles both Newton-system
reductions in that single read —

    per entity:  H = Xᵀ·diag(d2)·X     (MXU, d×d resident in VMEM)
                 g = Xᵀ·dz             (MXU, d resident in VMEM)

where the XLA lowering reads X twice (einsum Hessian + transpose matvec).
The Cholesky factorization, the Levenberg damping loop, and the trial-point
margin sweep stay in XLA — ``lax.linalg`` does not lower inside Mosaic, and
keeping the loop structure identical to the XLA path is what makes parity
bit-exact by construction (the kernel only replaces two reductions whose
per-entity values are reduction-order-identical to the vmapped einsum /
matmul; verified on CPU, pinned by tests/test_re_kernel.py).

The kernel is written UNBATCHED (one entity) and batched by ``jax.vmap``
inside ``_solve_block``'s ``vmap(solve_one)`` — pallas_call's batching rule
prepends the entity grid dimension, which is exactly the "one grid instance
per block row" shape, and it means every surrounding op (while_loop carry,
convergence select, quarantine) is shared verbatim with the XLA path.

bfloat16 X ("pallas_bf16x"): the kernel reads a bf16 copy of the slab
(halving the bandwidth-bound HBM read) and upcasts in VMEM; d2/dz and ALL
accumulation stay float32. Parity vs the f32 XLA path is then a pinned
tolerance, not bit-exact — see RE_KERNELS below and the BENCH_FULL.md
verdict table.

On-chip status: this module compiles the padded/tiled lowering only on a
real TPU backend (``padded=None`` auto). Every number and parity claim so
far is CPU interpret-mode (the r3–r5 TPU tunnel wedge, BENCH_FULL.md); the
on-chip run is pending.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.ops.pallas_glm import (  # noqa: F401  (re-exported gates)
    _SEQUENTIAL_GRID,
    _require_pallas,
    _tile_geometry,
    pallas_available,
    pallas_usable,
    pl,
)

Array = jax.Array

# Solver-kernel routing values for RandomEffectCoordinate.re_kernel /
# solve_cache.block_solver. "auto" resolves per backend; the other three are
# concrete lowerings:
#   xla          — vmapped einsum/matmul Newton system (2 X reads/iter)
#   pallas       — fused one-read Pallas Newton system, f32 X (bit-exact)
#   pallas_bf16x — same kernel over a bf16 X copy, f32 accumulate
#                  (pinned-tolerance parity; halves the slab's HBM read)
RE_KERNELS = ("auto", "xla", "pallas", "pallas_bf16x")


def resolve_re_kernel(re_kernel: str) -> str:
    """Concrete kernel for a requested routing value. ``auto`` picks the
    fused Pallas lowering only where it runs at full speed (a real TPU
    backend); everywhere else the XLA path wins — interpret-mode Pallas is
    orders of magnitude slower than XLA on CPU, so auto must never select
    it (tests and benches opt in explicitly)."""
    if re_kernel not in RE_KERNELS:
        raise ValueError(
            f"re_kernel must be one of {RE_KERNELS}, got {re_kernel!r}"
        )
    if re_kernel == "auto":
        return "pallas" if pallas_available() else "xla"
    return re_kernel


def _system_kernel(x_ref, d2_ref, dz_ref, h_ref, g_ref):
    """Whole-slab instance: both reductions from one read of x_ref.

    The einsum / matmul formulations are deliberately IDENTICAL to the XLA
    path in optim/newton.py — under vmap their per-entity values are
    bit-equal to the batched lowering (reduction-order parity verified on
    CPU), which is what lets the fused path claim bit-exact results."""
    x = x_ref[...]
    if x.dtype != jnp.float32:
        x = x.astype(jnp.float32)  # bf16 slab upcasts in VMEM; accum stays f32
    h_ref[...] = jnp.einsum("nd,n,ne->de", x, d2_ref[...], x)
    g_ref[...] = x.T @ dz_ref[...]


def _system_kernel_tiled(x_ref, d2_ref, dz_ref, h_ref, g_ref):
    """Row-tiled instance for slabs over the VMEM budget: sequential-grid
    accumulation (the pallas_glm reduction pattern), rank-2 operands for
    Mosaic layouts, preferred_element_type pins f32 accumulation."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        h_ref[:] = jnp.zeros_like(h_ref)
        g_ref[:] = jnp.zeros_like(g_ref)

    x = x_ref[:]
    if x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
    xd = x * d2_ref[:]  # (tile_n, d_pad) ∘ (tile_n, 1)
    h_ref[:] += jax.lax.dot_general(
        xd, x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    g_ref[:] += jax.lax.dot_general(
        x, dz_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def fused_newton_system(
    X: Array,
    d2: Array,
    dz: Array,
    interpret: Optional[bool] = None,
    padded: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """``(Xᵀ·diag(d2)·X, Xᵀ·dz)`` in ONE pass over ``X`` ((n, d), one
    entity; vmap for the batched per-block-row kernel).

    ``padded=None`` auto-selects: the exact unpadded whole-slab kernel in
    interpret mode (CPU — bit-exact vs the XLA formulations), the
    lane/sublane-padded tiled lowering when compiling for TPU (zero padding
    rows/columns contribute exactly zero to both reductions, but tiling
    re-associates the n-reduction, so on-chip parity is pinned-tolerance
    like bf16 — see module docstring)."""
    _require_pallas()
    n, d = X.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if padded is None:
        padded = not interpret
    if not padded:
        return pl.pallas_call(
            _system_kernel,
            out_shape=[
                jax.ShapeDtypeStruct((d, d), jnp.float32),
                jax.ShapeDtypeStruct((d,), jnp.float32),
            ],
            interpret=interpret,
        )(X, d2, dz)

    d_pad = int(np.ceil(max(d, 1) / 128) * 128)
    tile_n, n_pad = _tile_geometry(n, d_pad, X.dtype, n)
    if n_pad != n or d_pad != d:
        X = jnp.pad(X, ((0, n_pad - n), (0, d_pad - d)))
        d2 = jnp.pad(d2, (0, n_pad - n))
        dz = jnp.pad(dz, (0, n_pad - n))
    col = lambda v: v.astype(jnp.float32)[:, None]  # noqa: E731
    n_tiles = n_pad // tile_n
    h, g = pl.pallas_call(
        _system_kernel_tiled,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile_n, d_pad), lambda i: (i, 0)),  # X row tile
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),      # d2
            pl.BlockSpec((tile_n, 1), lambda i: (i, 0)),      # dz
        ],
        out_specs=[
            pl.BlockSpec((d_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((d_pad, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((d_pad, 1), jnp.float32),
        ],
        compiler_params=None if interpret else _SEQUENTIAL_GRID,
        interpret=interpret,
    )(X, col(d2), col(dz))
    return h[:d, :d], g[:d, 0]
