from photon_tpu.ops.losses import (  # noqa: F401
    PointwiseLoss,
    LogisticLoss,
    SquaredLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    loss_for_task,
)
from photon_tpu.ops.objective import GLMObjective  # noqa: F401
