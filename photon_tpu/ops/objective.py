"""GLM objective functions: value / gradient / Hessian-vector products.

Parity target: the reference's ObjectiveFunction hierarchy —
``ObjectiveFunction → DiffFunction → TwiceDiffFunction`` (photon-lib
function/ObjectiveFunction.scala:26, DiffFunction.scala:49,
TwiceDiffFunction.scala:34-60), the L2Regularization mixins
(L2Regularization.scala:26-255), and the four aggregators that compute
Σloss/Σgrad/H·v/diag(H)/H over distributed data
(photon-lib aggregators/*.scala).

TPU-first design: there is no aggregator layer at all. The objective is a pure
function ``w → Σ_i weight_i · loss(x_i·w + offset_i, y_i) + reg``; the gradient
is ``jax.grad``, the Hessian-vector product is a forward-over-reverse
``jax.jvp(jax.grad(f))``. Under ``jit`` with the batch sharded over a mesh's
sample axis, XLA inserts the cross-device reductions (the role of Spark
``treeAggregate``, reference ValueAndGradientAggregator.scala:300-321)
automatically; under ``shard_map`` the caller psums the outputs
(photon_tpu.parallel.distributed). Normalization is folded algebraically in
front of the margin matmul (see photon_tpu.data.normalization), exactly the
fold the reference derives by hand in ValueAndGradientAggregator.scala:41-148.

The **sum is weighted, not averaged**, matching the reference's aggregator
semantics (regularization weights are comparable across frameworks).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.data.normalization import NormalizationContext
from photon_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """Smooth part of a GLM objective (loss + L2). The L1 weight is carried
    here for OWL-QN (reference OWLQN.scala:39-70) but is NOT part of the
    smooth value/gradient, matching the reference split where Breeze's OWLQN
    owns the L1 term.

    ``intercept_index`` is excluded from both L1 and L2 regularization
    (reference L2Regularization.scala interceptOpt).
    """

    loss: PointwiseLoss = dataclasses.field(metadata=dict(static=True))
    l2_weight: float = 0.0
    l1_weight: float = 0.0
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )
    normalization: Optional[NormalizationContext] = None
    # Route dense value_and_grad through the fused Pallas kernel (one HBM
    # pass over X instead of XLA's two; photon_tpu.ops.pallas_glm). Falls
    # back automatically where the kernel doesn't apply (sparse features,
    # shift normalization, very wide dims). Since the round-4 FE bandwidth
    # A/B (bench --fe-bandwidth-ab) there is exactly one fused lowering —
    # tall rebalanced tiles on a sequential grid, fused one-pass HVP — and
    # it is the default for every fuse-eligible evaluation here; the
    # losing variants were deleted from pallas_glm, not kept behind flags.
    use_pallas: bool = dataclasses.field(default=False, metadata=dict(static=True))

    # ----- margins -----

    def margins(self, w: Array, batch: LabeledBatch) -> Array:
        if self.normalization is not None and not self.normalization.is_identity:
            ew, es = self.normalization.effective(w)
            return batch.margins(ew) + es
        return batch.margins(w)

    # ----- regularization -----

    def _l2_mask(self, w: Array) -> Array:
        if self.intercept_index is None:
            return w
        return w.at[self.intercept_index].set(0.0)

    def l2_term(self, w: Array) -> Array:
        if self.l2_weight == 0.0:
            return jnp.zeros((), w.dtype)
        wm = self._l2_mask(w)
        return 0.5 * self.l2_weight * jnp.dot(wm, wm)

    def l1_term(self, w: Array) -> Array:
        """Nonsmooth term, for reporting/OWL-QN only."""
        if self.l1_weight == 0.0:
            return jnp.zeros((), w.dtype)
        return self.l1_weight * jnp.sum(jnp.abs(self._l2_mask(w)))

    # ----- ObjectiveFunction.value -----

    def value(self, w: Array, batch: LabeledBatch) -> Array:
        z = self.margins(w, batch)
        return jnp.sum(batch.weight * self.loss.value(z, batch.label)) + self.l2_term(w)

    # ----- DiffFunction.calculate -----

    def value_and_grad(self, w: Array, batch: LabeledBatch) -> Tuple[Array, Array]:
        if self._can_fuse(batch):
            return self._pallas_value_and_grad(w, batch)
        return jax.value_and_grad(self.value)(w, batch)

    def _can_fuse(self, batch: LabeledBatch) -> bool:
        if not self.use_pallas:
            return False
        from photon_tpu.ops.pallas_glm import MAX_FUSED_DIM, pallas_usable

        # TPU-availability gate: when the pallas surface failed to import,
        # fall back to the XLA two-pass path instead of dying at dispatch.
        # (Off-TPU with a working import, the kernels run in interpreter
        # mode — slow, but exactly what the CPU smoke tests exercise.)
        if not pallas_usable():
            return False

        feats = batch.features
        if isinstance(feats, SparseFeatures) or feats.shape[1] > MAX_FUSED_DIM:
            return False
        # A pallas_call on a batch sharded over the mesh's data axis would
        # gather X to one device, silently defeating the data-parallel path
        # — require single-device data where the placement is visible
        # (concrete arrays). Sharded entry points must strip use_pallas
        # (glmix_sharded_train_step does) or shard_map around the solver.
        if isinstance(feats, jax.Array) and not isinstance(
            feats, jax.core.Tracer
        ):
            try:
                if len(feats.sharding.device_set) > 1:
                    return False
            except Exception:  # pragma: no cover - sharding introspection
                return False
        norm = self.normalization
        return norm is None or norm.shifts is None

    def _pallas_value_and_grad(self, w: Array, batch: LabeledBatch) -> Tuple[Array, Array]:
        from photon_tpu.ops.pallas_glm import fused_data_value_and_grad

        f = None if self.normalization is None else self.normalization.factors
        ew = w if f is None else w * f
        val, g = fused_data_value_and_grad(
            self.loss, ew, batch.features, batch.label, batch.offset, batch.weight
        )
        if f is not None:
            g = g * f
        if self.l2_weight != 0.0:
            val = val + self.l2_term(w)
            g = g + self.l2_weight * self._l2_mask(w)
        return val.astype(w.dtype), g.astype(w.dtype)

    def grad(self, w: Array, batch: LabeledBatch) -> Array:
        return jax.grad(self.value)(w, batch)

    # ----- TwiceDiffFunction.hessianVector (HessianVectorAggregator role) -----

    def hvp(self, w: Array, v: Array, batch: LabeledBatch) -> Array:
        """Forward-over-reverse Hessian-vector product: one extra fused pass,
        no Hessian materialization (reference HessianVectorAggregator.scala)."""
        return jax.jvp(lambda u: self.grad(u, batch), (w,), (v,))[1]

    def linearized_hvp(self, w: Array, batch: LabeledBatch):
        """Build ``v -> H(w)·v`` with all w-dependent state computed ONCE.

        The GLM Hessian at fixed ``w`` is H = Aᵀ·diag(d2)·A + λ·mask, where
        A = ∂margins/∂w is CONSTANT (margins is affine in w, normalization
        folding included) and d2 = weight·loss''(z, y) depends on w only
        through the margins z. The jvp-of-grad form recomputes z and the
        gradient inside every product (~4 X passes); here z/d2 are cached
        so each product is exactly one forward and one transpose pass —
        the same per-outer-iteration caching the reference's
        HessianVectorAggregator gets from broadcasting the fixed
        coefficients once per CG solve (HessianVectorAggregator.scala).
        Inner solvers (TRON's truncated CG) should prefer this via
        ``minimize_tron(hvp_factory=...)``.

        With ``use_pallas`` (and a fusible batch) each product runs the
        one-pass fused kernel (ops.pallas_glm.fused_data_hvp): forward and
        transpose matvec share a single HBM read of each X tile.
        """
        if self._can_fuse(batch):
            from photon_tpu.ops.pallas_glm import fused_data_hvp

            z = self.margins(w, batch)
            d2 = batch.weight * self.loss.dzz(z, batch.label)
            f = None if self.normalization is None else self.normalization.factors

            def hv_fused(v: Array) -> Array:
                ev = v if f is None else v * f
                out = fused_data_hvp(ev, batch.features, d2)
                if f is not None:
                    out = out * f
                if self.l2_weight != 0.0:
                    out = out + self.l2_weight * self._l2_mask(v)
                return out.astype(v.dtype)

            return hv_fused

        mfun = lambda ww: self.margins(ww, batch)  # noqa: E731
        z, lin = jax.linearize(mfun, w)
        # Transpose of the (already-linear) tangent map — no second forward
        # evaluation of the margins, unlike jax.vjp(mfun, w).
        lin_t = jax.linear_transpose(lin, w)
        d2 = batch.weight * self.loss.dzz(z, batch.label)

        def hv(v: Array) -> Array:
            out = lin_t(d2 * lin(v))[0]
            if self.l2_weight != 0.0:
                out = out + self.l2_weight * self._l2_mask(v)
            return out

        return hv

    # ----- TwiceDiffFunction.hessianDiagonal -----

    def hessian_diagonal(self, w: Array, batch: LabeledBatch) -> Array:
        """diag(H) = Σ_i weight_i · dzz_i · x_ij² (+λ), with normalization
        folded into effective features (HessianDiagonalAggregator.scala)."""
        z = self.margins(w, batch)
        d2 = batch.weight * self.loss.dzz(z, batch.label)
        feats = batch.features
        if self.normalization is not None and self.normalization.factors is not None:
            f = self.normalization.factors
        else:
            f = None
        if isinstance(feats, SparseFeatures):
            vals = feats.values
            if f is not None:
                vals = vals * f[feats.indices]
            if self.normalization is not None and self.normalization.shifts is not None:
                # Shifted sparse features densify; fall back to dense math.
                return self._hessian_diag_dense(feats.to_dense(), d2)
            contrib = (vals * vals) * d2[:, None]
            diag = jnp.zeros((feats.dim,), vals.dtype).at[feats.indices].add(contrib)
        else:
            diag = self._hessian_diag_dense(feats, d2)
        if self.l2_weight != 0.0:
            lam = jnp.full_like(diag, self.l2_weight)
            if self.intercept_index is not None:
                lam = lam.at[self.intercept_index].set(0.0)
            diag = diag + lam
        return diag

    def _hessian_diag_dense(self, X: Array, d2: Array) -> Array:
        if self.normalization is not None and not self.normalization.is_identity:
            f = self.normalization.factors
            s = self.normalization.shifts
            if f is not None:
                X = X * f[None, :]
            if s is not None:
                fs = s if f is None else s * f
                X = X - fs[None, :]
                if self.normalization.intercept_index is not None:
                    X = X.at[:, self.normalization.intercept_index].set(1.0)
        return jnp.einsum("n,nd->d", d2, X * X)

    # ----- TwiceDiffFunction.hessianMatrix (HessianMatrixAggregator role) -----

    def hessian_matrix(self, w: Array, batch: LabeledBatch) -> Array:
        """Full H = Xᵀ D X + λI — for variance computation on small problems
        (reference HessianMatrixAggregator.scala:34-157, no-normalization note
        :27-28 — here normalization IS supported via densified features)."""
        z = self.margins(w, batch)
        d2 = batch.weight * self.loss.dzz(z, batch.label)
        feats = batch.features
        X = feats.to_dense() if isinstance(feats, SparseFeatures) else feats
        if self.normalization is not None and not self.normalization.is_identity:
            f = self.normalization.factors
            s = self.normalization.shifts
            if f is not None:
                X = X * f[None, :]
            if s is not None:
                fs = s if f is None else s * f
                X = X - fs[None, :]
                if self.normalization.intercept_index is not None:
                    X = X.at[:, self.normalization.intercept_index].set(1.0)
        H = jnp.einsum("nd,n,ne->de", X, d2, X)
        if self.l2_weight != 0.0:
            lam = jnp.full((X.shape[1],), self.l2_weight, X.dtype)
            if self.intercept_index is not None:
                lam = lam.at[self.intercept_index].set(0.0)
            H = H + jnp.diag(lam)
        return H

    # ----- convenience -----

    def full_value(self, w: Array, batch: LabeledBatch) -> Array:
        """Smooth value + L1 term (the quantity OWL-QN minimizes)."""
        return self.value(w, batch) + self.l1_term(w)

    def with_l2(self, l2_weight: float) -> "GLMObjective":
        """Mutable-regularization-weight analogue for λ sweeps
        (reference DistributedOptimizationProblem.scala:63-74)."""
        return dataclasses.replace(self, l2_weight=l2_weight)

    def with_l1(self, l1_weight: float) -> "GLMObjective":
        return dataclasses.replace(self, l1_weight=l1_weight)
