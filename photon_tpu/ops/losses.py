"""Pointwise GLM loss functions.

Parity target: the reference's ``PointwiseLossFunction`` interface
(photon-lib function/glm/PointwiseLossFunction.scala:38-56) — per-sample loss
as a function of the margin ``z = x·w + offset`` and the label, with first
(``dz``) and second (``dzz``) derivatives w.r.t. the margin. Concrete losses:
LogisticLossFunction.scala:47-85, SquaredLossFunction.scala:32,
PoissonLossFunction.scala:31, plus the smoothed-hinge SVM task the reference
exposes via TaskType (README.md:105).

TPU-first design notes: each loss is a trio of elementwise jnp functions that
XLA fuses into the surrounding matmul (margin computation) — there is no
per-sample object or virtual dispatch. Everything is written to be stable in
float32/bfloat16 (softplus/sigmoid formulations rather than raw exp/log).

Label conventions match the reference: binary labels are 0/1 in data; the
logistic and smoothed-hinge losses internally map to the ±1 formulation.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A per-sample GLM loss l(z, y) with derivatives w.r.t. the margin z.

    Attributes:
      name: stable identifier (used in model metadata, mirrors the reference's
        ``lossFunction`` field in BayesianLinearModelAvro).
      value: (z, y) -> loss, elementwise.
      dz: (z, y) -> dl/dz, elementwise.
      dzz: (z, y) -> d2l/dz2, elementwise.
      mean: z -> E[y|z], the GLM inverse link (GeneralizedLinearModel mean
        function, reference supervised/model/GeneralizedLinearModel.scala).
    """

    name: str
    value: Callable[[Array, Array], Array]
    dz: Callable[[Array, Array], Array]
    dzz: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]


def _logistic_value(z: Array, y: Array) -> Array:
    # NLL of Bernoulli with logit z, y in {0,1}:
    #   l = softplus(z) - y*z  == log(1+e^z) - y*z
    # Stable for large |z| via jax.nn.softplus.
    return jax.nn.softplus(z) - y * z


def _logistic_dz(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - y


def _logistic_dzz(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LogisticLoss = PointwiseLoss(
    name="logisticLoss",
    value=_logistic_value,
    dz=_logistic_dz,
    dzz=_logistic_dzz,
    mean=jax.nn.sigmoid,
)


def _squared_value(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


SquaredLoss = PointwiseLoss(
    name="squaredLoss",
    value=_squared_value,
    dz=lambda z, y: z - y,
    dzz=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


def _poisson_value(z: Array, y: Array) -> Array:
    # NLL of Poisson with log-rate z (dropping the y!-normalizer, as the
    # reference does): l = exp(z) - y*z.
    return jnp.exp(z) - y * z


PoissonLoss = PointwiseLoss(
    name="poissonLoss",
    value=_poisson_value,
    dz=lambda z, y: jnp.exp(z) - y,
    dzz=lambda z, y: jnp.exp(z),
    mean=jnp.exp,
)


def _to_pm1(y: Array) -> Array:
    """Map {0,1} labels to {-1,+1}. Labels already ±1 pass through."""
    return jnp.where(y > 0, 1.0, -1.0)


def _smoothed_hinge_value(z: Array, y: Array) -> Array:
    # Rennie's smoothed hinge on t = y*z (y in ±1):
    #   t <= 0      : 1/2 - t
    #   0 < t < 1   : (1 - t)^2 / 2
    #   t >= 1      : 0
    t = _to_pm1(y) * z
    quad = 0.5 * jnp.square(jnp.maximum(1.0 - t, 0.0))
    lin = 0.5 - t
    return jnp.where(t <= 0.0, lin, jnp.where(t < 1.0, quad, jnp.zeros_like(t)))


def _smoothed_hinge_dz(z: Array, y: Array) -> Array:
    s = _to_pm1(y)
    t = s * z
    dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return s * dt


def _smoothed_hinge_dzz(z: Array, y: Array) -> Array:
    t = _to_pm1(y) * z
    return jnp.where((t > 0.0) & (t < 1.0), jnp.ones_like(t), jnp.zeros_like(t))


SmoothedHingeLoss = PointwiseLoss(
    name="smoothedHingeLoss",
    value=_smoothed_hinge_value,
    dz=_smoothed_hinge_dz,
    dzz=_smoothed_hinge_dzz,
    # Decision function, not a probability; sign(z) thresholded at 0.
    mean=lambda z: z,
)


_TASK_LOSSES = {
    TaskType.LOGISTIC_REGRESSION: LogisticLoss,
    TaskType.LINEAR_REGRESSION: SquaredLoss,
    TaskType.POISSON_REGRESSION: PoissonLoss,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss,
}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    """Task → loss dispatch (reference ObjectiveFunctionHelper.scala:40-70)."""
    return _TASK_LOSSES[task]
