"""Coefficient-variance computation (posterior diagnostics).

Parity target: reference ``DistributedOptimizationProblem.computeVariances``
(photon-api optimization/DistributedOptimizationProblem.scala:83-103) —
SIMPLE inverts the Hessian diagonal element-wise; FULL inverts the whole
Hessian (Cholesky, reference util/Linalg.scala:33-100 LAPACK dpotrs) and
takes its diagonal. Same split here, with the FULL path a batched
``cho_factor``/``cho_solve`` that vmaps cleanly over per-entity blocks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.types import VarianceComputationType

Array = jax.Array


def coefficient_variances(
    objective: GLMObjective,
    w: Array,
    batch: LabeledBatch,
    variance_type: VarianceComputationType,
) -> Optional[Array]:
    """Per-coefficient variances of the trained GLM, or None for NONE.

    SIMPLE: 1 / diag(H) — one Hessian-diagonal pass, O(d) memory.
    FULL:   diag(H⁻¹) via Cholesky — the proper marginal variances when
            coefficients are correlated; O(d²) memory, so suited to the
            fixed-effect and per-entity widths the reference applies it to.
    """
    if variance_type == VarianceComputationType.NONE:
        return None
    if variance_type == VarianceComputationType.SIMPLE:
        diag = objective.hessian_diagonal(w, batch)
        return 1.0 / jnp.maximum(diag, 1e-12)
    if variance_type == VarianceComputationType.FULL:
        H = objective.hessian_matrix(w, batch)
        return full_hessian_variances(H)
    raise ValueError(f"unknown variance type {variance_type!r}")


def full_hessian_variances(H: Array) -> Array:
    """diag(H⁻¹) through a Cholesky solve against I.

    A non-PD Hessian (unpenalized dead feature) yields NaN rows from
    ``cho_factor``; those coordinates fall back to the SIMPLE estimate so a
    single degenerate column cannot poison the whole vector.
    """
    d = H.shape[-1]
    chol, _ = jax.scipy.linalg.cho_factor(H, lower=True)
    inv = jax.scipy.linalg.cho_solve((chol, True), jnp.eye(d, dtype=H.dtype))
    full = jnp.diagonal(inv, axis1=-2, axis2=-1)
    simple = 1.0 / jnp.maximum(jnp.diagonal(H, axis1=-2, axis2=-1), 1e-12)
    return jnp.where(jnp.isfinite(full), full, simple)


def normalize_variance_type(value) -> VarianceComputationType:
    """Accept enum, string, bool (legacy --compute-variance flags), or None."""
    if isinstance(value, VarianceComputationType):
        return value
    if value is None or value is False:
        return VarianceComputationType.NONE
    if value is True:
        return VarianceComputationType.SIMPLE
    return VarianceComputationType(str(value).upper())
