"""OTLP-shaped span/metrics export: JSON-over-HTTP, protobuf-free.

The PR 14 plane is entirely in-process: the flight recorder answers
"what just went wrong *here*", but nothing leaves the box. This module
closes that edge with a batch exporter that POSTs OTLP-shaped JSON
(``resourceSpans`` / ``resourceMetrics``, the same envelope an OTLP/HTTP
collector accepts for JSON encoding) to ``<endpoint>/v1/traces`` and
``<endpoint>/v1/metrics`` — stdlib ``urllib`` only, because the
container has no protobuf/grpc and the degradation policy (model
artifacts > training progress > observability) forbids observability
from ever becoming a hard dependency.

Degradation contract, in order:
  - the hot path NEVER blocks: ``on_span`` is an O(1) enqueue under a
    lock; a full queue drops the span and counts it;
  - a flaky collector is retried with exponential backoff, a dead one
    costs one bounded retry cycle per batch and then the batch is
    DROPPED and counted (``dropped_batches``/``last_error``), visible in
    ``/healthz`` under ``otlp_exporter`` — never an exception, never a
    stall in scoring or training;
  - ``close()`` bounds its final drain, so driver shutdown cannot hang
    on an unreachable endpoint.

The exporter taps the tracer's sink mechanism (``Tracer.add_sink``),
which fires only for spans recorded under a sampled ``TraceContext`` —
untraced spans (the overwhelming majority under training) pay nothing.
Sinks survive ``begin_run()`` (the tracer reset keeps them), so drivers
install once, right after ``begin_run``.

``MockCollector`` is the stdlib in-process collector tests, ``ci.sh
obs`` and ``bench.py`` run against: it stores every decoded batch,
supports injected failures (``fail_next``) for the retry/drop paths, and
needs nothing outside ``http.server``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Callable, List, Optional, Tuple

from photon_tpu.obs.trace import SpanRecord, tracer

OTLP_TRACES_PATH = "/v1/traces"
OTLP_METRICS_PATH = "/v1/metrics"

# Queue/batch defaults: 2048 spans ≈ 4 full flight-recorder trace trees;
# bounded so a dead collector costs memory O(queue_cap), not O(uptime).
DEFAULT_QUEUE_CAP = 2048
DEFAULT_BATCH_MAX = 256
DEFAULT_FLUSH_INTERVAL_S = 0.5


def _hex_or_pad(value: Optional[str], width: int) -> str:
    """OTLP requires fixed-width lowercase hex ids; pad defensively so a
    hand-minted test id never produces an invalid document."""
    v = (value or "").lower()
    return v.rjust(width, "0")[:width]


def span_to_otlp(rec: SpanRecord, epoch_unix_s: float) -> dict:
    """One ``SpanRecord`` → one OTLP JSON span. ``start_s`` is relative
    to the tracer epoch; the wall epoch converts it to unix nanos."""
    start_ns = int((epoch_unix_s + rec.start_s) * 1e9)
    end_ns = start_ns + int(rec.duration_s * 1e9)
    out = {
        "traceId": _hex_or_pad(rec.trace_id, 32),
        "spanId": _hex_or_pad(rec.span_id, 16),
        "name": rec.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [
            {"key": "thread", "value": {"stringValue": rec.thread}},
        ],
    }
    if rec.parent_span_id:
        out["parentSpanId"] = _hex_or_pad(rec.parent_span_id, 16)
    if rec.pid is not None:
        out["attributes"].append(
            {"key": "pid", "value": {"intValue": str(rec.pid)}}
        )
    if rec.parent:
        out["attributes"].append(
            {"key": "parent_path", "value": {"stringValue": rec.parent}}
        )
    return out


def _otlp_attrs(labels: Optional[dict]) -> list:
    return [
        {"key": str(k), "value": {"stringValue": str(v)}}
        for k, v in sorted((labels or {}).items())
    ]


def snapshot_to_otlp(snapshot: List[dict], now_unix_ns: int) -> List[dict]:
    """A ``MetricsRegistry.snapshot()`` → OTLP JSON metric list.

    Counters map to monotonic sums, gauges to gauges, histograms to OTLP
    summary-style gauges carrying count/sum/quantile attributes (the
    registry keeps quantiles, not buckets — exporting what we actually
    measure beats inventing bucket boundaries). Exemplars ride along as
    OTLP exemplars with ``traceId`` so a collector can link back."""
    ts = str(now_unix_ns)
    out: List[dict] = []
    for snap in snapshot:
        name = snap.get("metric")
        kind = snap.get("type")
        attrs = _otlp_attrs(snap.get("labels"))
        if kind == "counter":
            out.append({
                "name": name,
                "sum": {
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "isMonotonic": True,
                    "dataPoints": [{
                        "timeUnixNano": ts,
                        "asDouble": float(snap.get("value") or 0),
                        "attributes": attrs,
                    }],
                },
            })
        elif kind == "gauge":
            out.append({
                "name": name,
                "gauge": {
                    "dataPoints": [{
                        "timeUnixNano": ts,
                        "asDouble": float(snap.get("value") or 0),
                        "attributes": attrs,
                    }],
                },
            })
        elif kind == "histogram":
            stats = snap.get("stats") or {}
            point = {
                "timeUnixNano": ts,
                "count": str(int(stats.get("count") or 0)),
                "sum": float(stats.get("sum") or 0.0),
                "attributes": attrs + [
                    {"key": f"quantile_{q}",
                     "value": {"doubleValue": float(stats[q])}}
                    for q in ("p50", "p95", "p99")
                    if stats.get(q) is not None
                ],
            }
            exemplars = stats.get("exemplars") or ()
            if exemplars:
                point["exemplars"] = [
                    {
                        "timeUnixNano": ts,
                        "asDouble": float(ex["value"]),
                        "traceId": _hex_or_pad(ex.get("traceId"), 32),
                    }
                    for ex in exemplars
                ]
            out.append({
                "name": name,
                "histogram": {
                    "aggregationTemporality": 2,
                    "dataPoints": [point],
                },
            })
    return out


class OTLPExporter:
    """Bounded-queue background exporter. One instance per process.

    ``on_span`` is the tracer sink (traced spans only); ``export_metrics``
    enqueues one registry snapshot as a batch. A single worker thread
    drains both, POSTing JSON with bounded retry + backoff; terminal
    failures drop-and-count. ``health()`` is the ``/healthz`` block."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "photon-tpu",
        queue_cap: int = DEFAULT_QUEUE_CAP,
        batch_max: int = DEFAULT_BATCH_MAX,
        flush_interval_s: float = DEFAULT_FLUSH_INTERVAL_S,
        timeout_s: float = 5.0,
        max_retries: int = 3,
        backoff_s: float = 0.1,
        backoff_max_s: float = 2.0,
        metrics_interval_s: float = 0.0,
        snapshot_fn: Optional[Callable[[], List[dict]]] = None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.queue_cap = queue_cap
        self.batch_max = batch_max
        self.flush_interval_s = flush_interval_s
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        # Periodic self-scrape: >0 → the worker snapshots the registry
        # every interval, so long-running drivers export without any
        # caller-side plumbing. snapshot_fn is injectable for tests.
        self.metrics_interval_s = metrics_interval_s
        self._snapshot_fn = snapshot_fn

        self._lock = threading.Lock()
        self._spans: deque = deque()
        self._metric_batches: deque = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()

        self.exported_spans = 0
        self.exported_span_batches = 0
        self.exported_metric_batches = 0
        self.dropped_spans = 0
        self.dropped_batches = 0
        self.consecutive_failures = 0
        self.last_error: Optional[str] = None
        self.last_export_unix_s: Optional[float] = None

        self._worker = threading.Thread(
            target=self._run, name="otlp-export", daemon=True
        )
        self._worker.start()

    # ---- intake (hot path) ------------------------------------------

    def on_span(self, rec: SpanRecord) -> None:
        """Tracer sink: O(1) enqueue; full queue drops-and-counts. Never
        raises (the tracer swallows sink errors anyway — this keeps the
        accounting honest instead of relying on that backstop)."""
        with self._lock:
            if len(self._spans) >= self.queue_cap:
                self.dropped_spans += 1
                return
            self._spans.append(rec)
            self._idle.clear()
        self._wake.set()

    def export_metrics(self, snapshot: Optional[List[dict]] = None) -> bool:
        """Enqueue one metrics snapshot as a batch; False if dropped."""
        if snapshot is None:
            snapshot = self._take_snapshot()
        if not snapshot:
            return True
        with self._lock:
            # Metrics batches are cumulative — a newer snapshot strictly
            # supersedes an older unsent one, so the queue bound sheds
            # the OLDEST batch (drop-and-count), keeping freshest state.
            if len(self._metric_batches) >= 8:
                self._metric_batches.popleft()
                self.dropped_batches += 1
            self._metric_batches.append(snapshot)
            self._idle.clear()
        self._wake.set()
        return True

    def _take_snapshot(self) -> List[dict]:
        if self._snapshot_fn is not None:
            return self._snapshot_fn()
        from photon_tpu.obs.metrics import registry

        return registry().snapshot()

    # ---- worker ------------------------------------------------------

    def _run(self) -> None:
        last_metrics = time.monotonic()
        while True:
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            if (
                self.metrics_interval_s > 0
                and time.monotonic() - last_metrics >= self.metrics_interval_s
            ):
                last_metrics = time.monotonic()
                try:
                    self.export_metrics()
                except Exception:  # noqa: BLE001 — never kill the worker
                    pass
            self._drain_once()
            if self._stop.is_set():
                self._drain_once()
                return

    def _drain_once(self) -> None:
        while True:
            with self._lock:
                batch = []
                while self._spans and len(batch) < self.batch_max:
                    batch.append(self._spans.popleft())
                metric_batch = (
                    self._metric_batches.popleft()
                    if not batch and self._metric_batches else None
                )
                if not batch and metric_batch is None:
                    self._idle.set()
                    return
            if batch:
                self._send_spans(batch)
            elif metric_batch is not None:
                self._send_metrics(metric_batch)

    def _send_spans(self, batch: List[SpanRecord]) -> None:
        epoch = tracer().epoch_unix_s
        payload = {
            "resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": self.service_name}},
                ]},
                "scopeSpans": [{
                    "scope": {"name": "photon_tpu.obs"},
                    "spans": [span_to_otlp(r, epoch) for r in batch],
                }],
            }],
        }
        if self._post(OTLP_TRACES_PATH, payload):
            self.exported_spans += len(batch)
            self.exported_span_batches += 1
        else:
            self.dropped_spans += len(batch)
            self.dropped_batches += 1

    def _send_metrics(self, snapshot: List[dict]) -> None:
        now_ns = int(time.time() * 1e9)
        payload = {
            "resourceMetrics": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": self.service_name}},
                ]},
                "scopeMetrics": [{
                    "scope": {"name": "photon_tpu.obs"},
                    "metrics": snapshot_to_otlp(snapshot, now_ns),
                }],
            }],
        }
        if self._post(OTLP_METRICS_PATH, payload):
            self.exported_metric_batches += 1
        else:
            self.dropped_batches += 1

    def _post(self, path: str, payload: dict) -> bool:
        body = json.dumps(payload).encode("utf-8")
        delay = self.backoff_s
        for attempt in range(self.max_retries):
            if attempt and self._stop.is_set():
                break  # shutdown: one try, no backoff sleeps
            try:
                req = urllib.request.Request(
                    self.endpoint + path, data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    pass
                self.consecutive_failures = 0
                self.last_export_unix_s = time.time()
                return True
            except Exception as exc:  # noqa: BLE001 — degrade, never raise
                self.last_error = f"{type(exc).__name__}: {exc}"[:200]
                self.consecutive_failures += 1
                if attempt + 1 < self.max_retries:
                    time.sleep(delay)
                    delay = min(delay * 2, self.backoff_max_s)
        return False

    # ---- lifecycle / introspection ----------------------------------

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the queue drains (or timeout). Test/bench helper —
        production paths never wait on the exporter."""
        self._wake.set()
        return self._idle.wait(timeout_s)

    def close(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout_s)

    def health(self) -> dict:
        with self._lock:
            depth = len(self._spans) + len(self._metric_batches)
        return {
            "endpoint": self.endpoint,
            "queue_depth": depth,
            "queue_cap": self.queue_cap,
            "exported_spans": self.exported_spans,
            "exported_span_batches": self.exported_span_batches,
            "exported_metric_batches": self.exported_metric_batches,
            "dropped_spans": self.dropped_spans,
            "dropped_batches": self.dropped_batches,
            "consecutive_failures": self.consecutive_failures,
            "last_error": self.last_error,
            "last_export_unix_s": self.last_export_unix_s,
        }


# ---- process-global registry ----------------------------------------
#
# One exporter per process, installed by the driver right after
# begin_run(). Tracer sinks survive begin_run's tracer reset, so the
# subscription holds for the whole process lifetime.

_ACTIVE: Optional[OTLPExporter] = None
_ACTIVE_LOCK = threading.Lock()


def install_exporter(exporter: OTLPExporter) -> OTLPExporter:
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev, _ACTIVE = _ACTIVE, exporter
    if prev is not None:
        tracer().remove_sink(prev.on_span)
        prev.close(timeout_s=1.0)
    tracer().add_sink(exporter.on_span)
    return exporter


def active_exporter() -> Optional[OTLPExporter]:
    return _ACTIVE


def uninstall_exporter() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        exporter, _ACTIVE = _ACTIVE, None
    if exporter is not None:
        tracer().remove_sink(exporter.on_span)
        exporter.close(timeout_s=1.0)


def exporter_health() -> Optional[dict]:
    """The ``/healthz`` ``otlp_exporter`` block; None when no exporter is
    installed (the block is omitted, matching pre-PR-15 payloads)."""
    exporter = _ACTIVE
    return None if exporter is None else exporter.health()


def maybe_install_exporter(
    endpoint: Optional[str], service_name: str, **kwargs
) -> Optional[OTLPExporter]:
    """Driver entry: ``--otlp-endpoint`` wiring in one line. Falsy
    endpoint → no-op (observability stays fully in-process)."""
    if not endpoint:
        return None
    return install_exporter(
        OTLPExporter(endpoint, service_name=service_name, **kwargs)
    )


# ---- mock collector --------------------------------------------------


class MockCollector:
    """Stdlib in-process OTLP collector for tests/bench/CI.

    Stores every decoded batch; ``fail_next(n)`` makes the next ``n``
    requests answer 503 (retry/backoff drills); ``port=0`` binds an
    ephemeral port. Runs a daemon ThreadingHTTPServer — ``close()`` when
    done."""

    def __init__(self, port: int = 0):
        import http.server

        collector = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — http.server API
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                with collector._lock:
                    collector.requests_total += 1
                    if collector._fail_budget > 0:
                        collector._fail_budget -= 1
                        self.send_response(503)
                        self.end_headers()
                        return
                    try:
                        payload = json.loads(raw.decode("utf-8"))
                    except Exception:  # noqa: BLE001
                        self.send_response(400)
                        self.end_headers()
                        return
                    if self.path == OTLP_TRACES_PATH:
                        collector.span_batches.append(payload)
                    elif self.path == OTLP_METRICS_PATH:
                        collector.metric_batches.append(payload)
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *args):  # quiet
                pass

        self._lock = threading.Lock()
        self.span_batches: List[dict] = []
        self.metric_batches: List[dict] = []
        self.requests_total = 0
        self._fail_budget = 0
        self._server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", port), Handler
        )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mock-otlp", daemon=True
        )
        self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._server.server_address[1]}"

    def fail_next(self, n: int) -> None:
        with self._lock:
            self._fail_budget = n

    def spans(self) -> List[dict]:
        """All received OTLP spans, flattened across batches."""
        out = []
        with self._lock:
            batches = list(self.span_batches)
        for payload in batches:
            for rs in payload.get("resourceSpans", ()):
                for ss in rs.get("scopeSpans", ()):
                    out.extend(ss.get("spans", ()))
        return out

    def metrics(self) -> List[dict]:
        """All received OTLP metrics, flattened across batches."""
        out = []
        with self._lock:
            batches = list(self.metric_batches)
        for payload in batches:
            for rm in payload.get("resourceMetrics", ()):
                for sm in rm.get("scopeMetrics", ()):
                    out.extend(sm.get("metrics", ()))
        return out

    def metric_exemplar_trace_ids(self) -> List[Tuple[str, str]]:
        """(metric_name, traceId) for every exemplar received."""
        out = []
        for m in self.metrics():
            for dp in (m.get("histogram") or {}).get("dataPoints", ()):
                for ex in dp.get("exemplars", ()):
                    out.append((m["name"], ex.get("traceId")))
        return out

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
