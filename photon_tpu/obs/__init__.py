"""Unified run telemetry: trace spans + metrics registry + JSONL run report.

Three pieces, one artifact:

- :mod:`photon_tpu.obs.trace` — hierarchical host-wall spans
  (``span("cd/iter3/per-user/solve")``), thread-safe, nestable across the
  ingest pipeline's stage threads.
- :mod:`photon_tpu.obs.metrics` — process-global counters / gauges /
  histograms with labels; the solve cache, pipeline stages, replay cache,
  shape bucketing, and optimizers all publish here.
- :mod:`photon_tpu.obs.report` — the run-report finalizer: spans + metrics
  + coordinate-descent tracker + environment as schema-stable JSONL
  (``--telemetry-out`` on every CLI driver) and as
  ``PhotonOptimizationLogEvent`` payloads.

Drivers call :func:`begin_run` at entry (fresh spans/metrics/phase timers —
stale state from a previous in-process invocation never leaks into this
run's report) and ``finalize_run_report`` at exit.
"""

from photon_tpu.obs.export import (  # noqa: F401
    MockCollector,
    OTLPExporter,
    active_exporter,
    exporter_health,
    install_exporter,
    maybe_install_exporter,
    uninstall_exporter,
)
from photon_tpu.obs.metrics import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    MetricsRegistry,
    registry,
    render_prometheus,
    reset_registry,
)
from photon_tpu.obs.report import (  # noqa: F401
    TELEMETRY_SCHEMA,
    collect_run_records,
    finalize_run_report,
    telemetry_sink_health,
    validate_record,
    write_run_report,
)
from photon_tpu.obs.slo import SLOTracker  # noqa: F401
from photon_tpu.obs.trace import (  # noqa: F401
    FlightRecorder,
    SpanRecord,
    TraceContext,
    attach_context,
    current_span_path,
    extract_context,
    flight_recorder,
    get_spans,
    merge_trace_dumps,
    mint_context,
    record_span,
    reset_flight_recorder,
    reset_tracer,
    span,
    tracer,
)


def begin_run() -> None:
    """Reset all run-scoped telemetry state: spans, registry metrics, the
    ``Timed`` phase records, and the shared solve-cache counters (compiled
    executables are kept — only the counters are run-scoped), so a second
    driver invocation in one process starts from a clean slate."""
    from photon_tpu.algorithm.solve_cache import default_cache
    from photon_tpu.utils.timed import Timed

    reset_tracer()
    reset_flight_recorder()
    reset_registry()
    Timed.reset()
    default_cache().reset_stats()
