"""Process-global metrics registry: counters, gauges, histograms with labels.

The registry is where every subsystem's counters LAND — solve-cache
traces/hits/calls per key, pipeline stage busy/starved/backpressured wall,
replay-cache bytes and spills, shape-bucket pad waste per dim, optimizer
iterations and convergence reasons — replacing the habit of each subsystem
growing a private stats dataclass nobody else can find. The private
dataclasses (``SolveCacheStats``, ``StageStats``, …) remain as the cheap
accumulation mechanism on their hot paths and PUBLISH here at natural
flush points (pipeline finalize, report finalize), so reading the registry
never perturbs a hot loop.

Instruments are keyed by ``(name, sorted(labels))``; every mutation takes
the instrument's own lock, so concurrent stage threads can increment the
same counter without losing updates (tests/test_telemetry.py hammers this).
Values are plain Python numbers — publishing a device array here would
force a host sync, so callers convert exactly once, at finalize.

Naming convention (audited PR 14; new instruments MUST follow it):

- Counters end in ``_total`` (``serve_requests_total``). A counter counts
  events or monotonically-accumulated quantities; byte accumulators are
  counters too (``re_store_upload_bytes_total``).
- The unit is a suffix, and it is the LAST suffix before ``_total``:
  seconds are ``_s`` (``serve_queue_wait_s``), bytes are ``_bytes``
  (``host_rss_bytes``). ``_seconds`` and unit-then-qualifier orderings
  (``model_staleness_s_hist``) are legacy; renamed instruments keep a
  read-alias in ``CANONICAL_NAMES`` so old call sites and dashboards
  resolve to the SAME instrument under the new name.
- Serve-path instruments carry a ``replica`` label: fleet replicas stamp
  it via ``set_default_labels(replica=<id>)`` at process start; the
  frontend's own instruments get ``replica="frontend"`` filled in at
  ``/metrics`` render time (``render_prometheus(extra_labels=...)``),
  so one merged scrape never mixes two processes' series.

``render_prometheus`` turns ``snapshot()`` dicts (ours or a fleet
replica's, shipped over the scrape op) into Prometheus text exposition
format v0.0.4: counters/gauges verbatim, histograms as summaries
(``{quantile=...}`` + ``_sum``/``_count``).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

# Legacy instrument name -> canonical name. Both spellings address the
# SAME instrument (the registry canonicalizes on every lookup), and
# snapshots/renders emit only the canonical name.
CANONICAL_NAMES: Dict[str, str] = {
    "re_entities_skipped": "re_entities_skipped_total",
    "pipeline_wall_seconds": "pipeline_wall_s",
    "pipeline_stage_busy_seconds": "pipeline_stage_busy_s",
    "pipeline_stage_starved_seconds": "pipeline_stage_starved_s",
    "pipeline_stage_backpressured_seconds": "pipeline_stage_backpressured_s",
    "model_staleness_s_hist": "model_staleness_hist_s",
}


def canonical_name(name: str) -> str:
    return CANONICAL_NAMES.get(name, name)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    kind = "instrument"

    def __init__(self, name: str, labels: LabelKey):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()

    def label_dict(self) -> Dict[str, str]:
        return dict(self.labels)


class Counter(_Instrument):
    """Monotonically-increasing count (events, items, bytes)."""

    kind = "counter"

    def __init__(self, name: str, labels: LabelKey):
        super().__init__(name, labels)
        self._value = 0

    def inc(self, amount: float = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def as_dict(self) -> dict:
        return dict(record="metric", metric=self.name, type=self.kind,
                    labels=self.label_dict(), value=self.value, stats=None)


class Gauge(_Instrument):
    """Last-write-wins value (occupancy, cached bytes, wall seconds)."""

    kind = "gauge"

    def __init__(self, name: str, labels: LabelKey):
        super().__init__(name, labels)
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def add(self, amount: float) -> None:
        with self._lock:
            self._value = (self._value or 0) + amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def as_dict(self) -> dict:
        return dict(record="metric", metric=self.name, type=self.kind,
                    labels=self.label_dict(), value=self.value, stats=None)


class Histogram(_Instrument):
    """Streaming summary (count/sum/min/max) plus a bounded DETERMINISTIC
    reservoir for percentile estimates (p50/p95/p99 — the latency columns a
    serving report is useless without).

    The reservoir keeps every observation until it reaches capacity, then
    halves itself (every 2nd element) and doubles its sampling stride, so it
    always holds an evenly-strided subsample of the full sequence in
    arrival order. Deterministic by construction — no RNG — so two runs over
    the same observation sequence report the same percentiles, and memory is
    bounded at ``RESERVOIR_CAP`` floats regardless of observation count.
    Percentiles are exact below the cap and stride-approximate above it."""

    kind = "histogram"
    RESERVOIR_CAP = 4096
    # Exemplar store: a handful of (value, trace_id) pairs linking the
    # series to flight-recorder traces. Same deterministic keep-every-
    # stride / halve-and-double scheme as the value reservoir (no RNG):
    # two runs over the same traced sequence keep the same exemplars.
    EXEMPLAR_CAP = 8

    def __init__(self, name: str, labels: LabelKey):
        super().__init__(name, labels)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._sample: List[float] = []
        self._stride = 1
        self._since_kept = 0
        self._exemplars: List[Tuple[float, str]] = []
        self._ex_stride = 1
        self._ex_since = 0

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._since_kept += 1
            if self._since_kept >= self._stride:
                self._since_kept = 0
                self._sample.append(v)
                if len(self._sample) >= self.RESERVOIR_CAP:
                    self._sample = self._sample[::2]
                    self._stride *= 2
            if trace_id:
                self._ex_since += 1
                if self._ex_since >= self._ex_stride:
                    self._ex_since = 0
                    self._exemplars.append((v, trace_id))
                    if len(self._exemplars) >= self.EXEMPLAR_CAP:
                        self._exemplars = self._exemplars[::2]
                        self._ex_stride *= 2

    def exemplars(self) -> List[dict]:
        """Kept (value, trace_id) pairs, oldest first. The LAST one is
        what the Prometheus render attaches (freshest link)."""
        with self._lock:
            return [
                {"value": v, "traceId": tid} for v, tid in self._exemplars
            ]

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None

    @staticmethod
    def _quantile(ordered: List[float], q: float) -> float:
        """Linear interpolation between closest ranks (numpy's default)."""
        pos = q * (len(ordered) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ordered) - 1)
        return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)

    def percentiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, Optional[float]]:
        with self._lock:
            ordered = sorted(self._sample)
        if not ordered:
            return {f"p{round(q * 100)}": None for q in qs}
        return {
            f"p{round(q * 100)}": self._quantile(ordered, q) for q in qs
        }

    def as_dict(self) -> dict:
        pcts = self.percentiles()
        with self._lock:
            stats = dict(
                count=self.count,
                sum=self.sum,
                min=self.min,
                max=self.max,
                mean=self.sum / self.count if self.count else None,
                **pcts,
            )
            if self._exemplars:
                # ``stats`` is an OPEN dict in the report schema
                # (obs/report.py validates the envelope, not stats keys),
                # so exemplars ride the existing record shape.
                stats["exemplars"] = [
                    {"value": v, "traceId": tid}
                    for v, tid in self._exemplars
                ]
        return dict(record="metric", metric=self.name, type=self.kind,
                    labels=self.label_dict(), value=None, stats=stats)


class MetricsRegistry:
    """Label-aware instrument store. ``counter/gauge/histogram`` create on
    first use and return the same instrument for the same (name, labels)
    thereafter; a name cannot change kind."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, LabelKey], _Instrument] = {}
        self._default_labels: Dict[str, str] = {}

    def set_default_labels(self, **labels) -> None:
        """Labels merged into every instrument created AFTER this call —
        how a fleet replica stamps ``replica=<id>`` on all its serve
        metrics without threading the id through every call site. Explicit
        labels win on collision; passing nothing clears the defaults.
        Set once at process start (before instruments exist): instruments
        created earlier keep their original label sets."""
        with self._lock:
            self._default_labels = {
                str(k): str(v) for k, v in labels.items()
            }

    def default_labels(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._default_labels)

    def _get(self, cls, name: str, labels: Dict[str, object]):
        name = canonical_name(name)
        if self._default_labels:
            labels = {**self._default_labels, **labels}
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, key[1])
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(Histogram, name, labels)

    def find(self, name: str, **labels) -> Optional[_Instrument]:
        """Lookup without creating (tests, bench readers). Default labels
        are merged the same way ``_get`` merges them, so an in-process
        reader addresses instruments by the labels IT passed at creation."""
        name = canonical_name(name)
        if self._default_labels:
            labels = {**self._default_labels, **labels}
        with self._lock:
            return self._instruments.get((name, _label_key(labels)))

    def collect(self, prefix: str = "") -> List[_Instrument]:
        with self._lock:
            return [
                inst
                for (name, _), inst in sorted(self._instruments.items())
                if name.startswith(prefix)
            ]

    def snapshot(self) -> List[dict]:
        """One report-ready dict per instrument (the ``metric`` JSONL
        record shape)."""
        return [inst.as_dict() for inst in self.collect()]

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()
            self._default_labels = {}


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global registry every subsystem publishes into."""
    return _REGISTRY


def reset_registry() -> None:
    _REGISTRY.reset()


# -- Prometheus text exposition -------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def prometheus_name(name: str) -> str:
    """Canonicalize then sanitize to the Prometheus metric-name charset."""
    name = _PROM_NAME_BAD.sub("_", canonical_name(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _prom_label_value(value: object) -> str:
    s = str(value)
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _prom_labels(labels: Dict[str, object]) -> str:
    if not labels:
        return ""
    parts = [
        f'{_PROM_LABEL_BAD.sub("_", str(k))}="{_prom_label_value(v)}"'
        for k, v in sorted(labels.items(), key=lambda kv: str(kv[0]))
    ]
    return "{" + ",".join(parts) + "}"


def _prom_number(value) -> str:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
    snapshots: Iterable[dict],
    extra_labels: Optional[Dict[str, str]] = None,
) -> str:
    """Snapshot dicts (``MetricsRegistry.snapshot()`` shape — local or
    shipped from a fleet replica over the scrape op) -> Prometheus text
    exposition format v0.0.4.

    ``extra_labels`` FILL IN where absent (existing labels win): the
    frontend stamps ``replica="frontend"`` on its own instruments this way
    so the merged fleet scrape keeps every serve-path series disambiguated
    by replica. Counters/gauges render verbatim; histograms render as
    summaries (quantile series + ``_sum``/``_count``). Series are grouped
    by name so each metric gets exactly one ``# TYPE`` header even when
    several processes contribute."""
    extra = {str(k): str(v) for k, v in (extra_labels or {}).items()}
    grouped: "Dict[str, List[dict]]" = {}
    order: List[str] = []
    for snap in snapshots:
        if not isinstance(snap, dict) or snap.get("record") != "metric":
            continue
        name = prometheus_name(str(snap.get("metric", "")))
        if name not in grouped:
            grouped[name] = []
            order.append(name)
        grouped[name].append(snap)
    lines: List[str] = []
    for name in order:
        snaps = grouped[name]
        kind = snaps[0].get("type", "gauge")
        prom_type = {"counter": "counter", "histogram": "summary"}.get(
            str(kind), "gauge"
        )
        lines.append(f"# TYPE {name} {prom_type}")
        for snap in snaps:
            labels = dict(snap.get("labels") or {})
            for k, v in extra.items():
                labels.setdefault(k, v)
            if snap.get("type") == "histogram":
                stats = snap.get("stats") or {}
                for pkey, q in _QUANTILES:
                    val = stats.get(pkey)
                    if val is None:
                        continue
                    qlabels = dict(labels)
                    qlabels["quantile"] = q
                    lines.append(
                        f"{name}{_prom_labels(qlabels)} {_prom_number(val)}"
                    )
                lines.append(
                    f"{name}_sum{_prom_labels(labels)} "
                    f"{_prom_number(stats.get('sum', 0.0))}"
                )
                count_line = (
                    f"{name}_count{_prom_labels(labels)} "
                    f"{_prom_number(stats.get('count', 0))}"
                )
                # OpenMetrics exemplar: link the freshest kept
                # (value, trace_id) pair to the series so a scrape can
                # jump from a latency bucket straight to the flight-
                # recorder trace (photon-tpu-obs traces <trace_id>).
                exemplars = stats.get("exemplars")
                if exemplars:
                    ex = exemplars[-1]
                    count_line += (
                        f' # {{trace_id="{ex["traceId"]}"}}'
                        f' {_prom_number(ex["value"])}'
                    )
                lines.append(count_line)
            else:
                value = snap.get("value")
                if value is None:
                    continue
                lines.append(
                    f"{name}{_prom_labels(labels)} {_prom_number(value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")
