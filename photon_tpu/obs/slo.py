"""SLO plane: availability, latency, and freshness objectives with
multi-window burn-rate alerting state.

An SLO here is a RATIO objective over discrete events: each event is good
or bad (request succeeded; latency under the bar; model staleness under
the bar), and the objective says what fraction must be good
(``target``, e.g. 0.999). The error budget is ``1 - target``; the burn
rate over a window is ``bad_fraction / budget`` — 1.0 means "spending the
budget exactly as fast as the SLO allows", 14.4 means "the whole 30-day
budget would be gone in ~2 days".

Alerting state follows the standard multiwindow-multi-burn-rate scheme
(Google SRE workbook): PAGE when the burn exceeds a high threshold over
BOTH a long and a short window (the short window makes the alert reset
fast once the bleeding stops), WARN on a lower threshold over slower
windows. The thresholds/windows are constructor knobs so the CI drill can
run the state machine in seconds with an injected clock.

Events land in a time-bucketed ring (fixed bucket width, horizon = the
longest window), so memory is bounded and recording is O(1). Everything is
host-side integer math — safe to call from serve completion callbacks
without violating the sync-free dispatch rule.

``SLOTracker.snapshot()`` is the ``/healthz`` block; ``publish_metrics()``
mirrors burn rates and numeric states into the metrics registry so the
``/metrics`` scrape carries them fleet-wide.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from photon_tpu.obs.metrics import MetricsRegistry, registry

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_PAGE = "page"
STATE_LEVEL = {STATE_OK: 0, STATE_WARN: 1, STATE_PAGE: 2}

# (long_window_s, short_window_s, burn_threshold) — both windows must
# exceed the threshold for the rule to fire.
DEFAULT_PAGE_RULES: Tuple[Tuple[float, float, float], ...] = (
    (3600.0, 300.0, 14.4),
)
DEFAULT_WARN_RULES: Tuple[Tuple[float, float, float], ...] = (
    (21600.0, 1800.0, 6.0),
)

# Second-scale rules for drills and CI: the same state machine, but with
# windows a test can traverse in wall time — a sustained burn pages in a
# few seconds and CLEARS a few seconds after the bleeding stops (the
# short window is what resets the page).
DRILL_PAGE_RULES: Tuple[Tuple[float, float, float], ...] = (
    (30.0, 5.0, 10.0),
)
DRILL_WARN_RULES: Tuple[Tuple[float, float, float], ...] = (
    (60.0, 10.0, 5.0),
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One ratio SLO. ``threshold`` is the per-event bar for value-based
    objectives (latency seconds, staleness seconds); None for pure
    success/failure objectives like availability."""

    name: str
    target: float
    threshold: Optional[float] = None
    unit: Optional[str] = None

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def default_objectives(
    availability_target: float = 0.999,
    latency_threshold_s: float = 0.5,
    latency_target: float = 0.99,
    staleness_threshold_s: float = 120.0,
    staleness_target: float = 0.99,
) -> List[Objective]:
    return [
        Objective("availability", availability_target),
        Objective("latency_p99", latency_target, latency_threshold_s, "s"),
        Objective(
            "model_staleness_s", staleness_target, staleness_threshold_s, "s"
        ),
    ]


def streaming_objectives(
    cycle_target: float = 0.95,
    staleness_threshold_s: float = 120.0,
    staleness_target: float = 0.99,
    fe_age_threshold_s: float = 3600.0,
    fe_age_target: float = 0.95,
) -> List[Objective]:
    """The updater-side SLO plane: micro-generation cycle success ratio
    plus published-model freshness — measurable with NO server running
    (the serve-side staleness objective only ticks at promote time) —
    plus the locked-fixed-effect age objective. Streaming deltas never
    retrain the FE, so its age grows monotonically between full publishes;
    once cycles observe it past the bar the burn machinery turns sustained
    violation into warn/page state, which is what the updater's
    ``stream_fe_retrain_wanted`` trigger keys off."""
    return [
        Objective("update_cycle", cycle_target),
        Objective(
            "model_staleness_s", staleness_target, staleness_threshold_s, "s"
        ),
        Objective("fe_age_s", fe_age_target, fe_age_threshold_s, "s"),
    ]


def quality_objectives(
    auc_target: float = 0.99,
    calibration_target: float = 0.99,
) -> List[Objective]:
    """The model-quality objectives (obs/quality.py): per-event good/bad
    comes from the quality plane — good while the windowed online AUC stays
    within ``auc_drop_bound`` of the frozen baseline's, and while windowed
    ECE stays under ``ece_bound``. No per-event value threshold here: the
    quality plane already applied its bars; these objectives only run the
    multi-window burn machinery, so a paging ``auc_drop`` drives the SAME
    rollout-watcher actuation (abort shadow / rollback / freeze) as any
    operational page."""
    return [
        Objective("auc_drop", auc_target),
        Objective("calibration_drift", calibration_target),
    ]


class _BucketRing:
    """Time-bucketed (good, bad) counts over a bounded horizon. Buckets are
    ``bucket_s`` wide; entries older than the horizon are trimmed on every
    touch, so memory is O(horizon / bucket_s) regardless of event rate."""

    def __init__(self, bucket_s: float, horizon_s: float):
        self.bucket_s = bucket_s
        self.max_buckets = int(math.ceil(horizon_s / bucket_s)) + 1
        self._buckets: List[List[float]] = []  # [bucket_idx, good, bad]

    def add(self, good: bool, now: float) -> None:
        idx = int(now // self.bucket_s)
        if self._buckets and self._buckets[-1][0] == idx:
            slot = self._buckets[-1]
        else:
            slot = [idx, 0, 0]
            self._buckets.append(slot)
            floor = idx - self.max_buckets
            while self._buckets and self._buckets[0][0] <= floor:
                self._buckets.pop(0)
        if good:
            slot[1] += 1
        else:
            slot[2] += 1

    def totals(self, window_s: float, now: float) -> Tuple[int, int]:
        """(good, bad) over the trailing window. Bucket-granular: a bucket
        counts iff it starts inside the window."""
        floor = int((now - window_s) // self.bucket_s)
        good = bad = 0
        for idx, g, b in reversed(self._buckets):
            if idx <= floor:
                break
            good += g
            bad += b
        return int(good), int(bad)


class SLOTracker:
    """Burn-rate state for a set of ratio objectives. One instance lives on
    the serving engine; fleet replicas each run their own (their snapshots
    ride the ``stats`` scrape like every other per-replica block)."""

    def __init__(
        self,
        objectives: Optional[Sequence[Objective]] = None,
        page_rules: Sequence[Tuple[float, float, float]] = DEFAULT_PAGE_RULES,
        warn_rules: Sequence[Tuple[float, float, float]] = DEFAULT_WARN_RULES,
        bucket_s: float = 5.0,
        min_events: int = 10,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.objectives: Dict[str, Objective] = {
            o.name: o for o in (objectives or default_objectives())
        }
        self.page_rules = tuple(page_rules)
        self.warn_rules = tuple(warn_rules)
        self.min_events = min_events
        self._clock = clock
        horizon = max(
            [w for rule in self.page_rules + self.warn_rules for w in rule[:2]]
            or [3600.0]
        )
        self._lock = threading.Lock()
        self._rings: Dict[str, _BucketRing] = {
            name: _BucketRing(bucket_s, horizon) for name in self.objectives
        }
        self._events: Dict[str, int] = {name: 0 for name in self.objectives}

    # -- recording ---------------------------------------------------------

    def record_event(
        self, objective: str, good: bool, now: Optional[float] = None
    ) -> None:
        ring = self._rings.get(objective)
        if ring is None:
            return
        t = self._clock() if now is None else now
        with self._lock:
            ring.add(good, t)
            self._events[objective] += 1

    def record_request(
        self,
        ok: bool,
        latency_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> None:
        """One serve completion: feeds availability always, the latency
        objective when the request succeeded with a measured latency
        (failed requests shouldn't double-count against latency)."""
        t = self._clock() if now is None else now
        self.record_event("availability", ok, now=t)
        if ok and latency_s is not None:
            obj = self.objectives.get("latency_p99")
            if obj is not None and obj.threshold is not None:
                self.record_event(
                    "latency_p99", latency_s <= obj.threshold, now=t
                )

    def record_staleness(
        self, staleness_s: float, now: Optional[float] = None
    ) -> None:
        obj = self.objectives.get("model_staleness_s")
        if obj is not None and obj.threshold is not None:
            self.record_event(
                "model_staleness_s", staleness_s <= obj.threshold, now=now
            )

    def record_fe_age(
        self, age_s: float, now: Optional[float] = None
    ) -> None:
        """One observation of the locked fixed effect's age — good while
        under the objective's threshold. Observed once per update cycle,
        so the multi-window burn state reflects SUSTAINED staleness, not a
        single slow full retrain."""
        obj = self.objectives.get("fe_age_s")
        if obj is not None and obj.threshold is not None:
            self.record_event("fe_age_s", age_s <= obj.threshold, now=now)

    # -- burn / state ------------------------------------------------------

    def _burn(self, objective: str, window_s: float, now: float) -> Optional[float]:
        obj = self.objectives[objective]
        with self._lock:
            good, bad = self._rings[objective].totals(window_s, now)
        total = good + bad
        if total == 0:
            return None
        return (bad / total) / obj.budget

    def burn_rates(
        self, objective: str, now: Optional[float] = None
    ) -> Dict[str, Optional[float]]:
        t = self._clock() if now is None else now
        windows = sorted(
            {w for rule in self.page_rules + self.warn_rules for w in rule[:2]}
        )
        return {
            _window_name(w): self._burn(objective, w, t) for w in windows
        }

    def state(self, objective: str, now: Optional[float] = None) -> str:
        """Multiwindow-multi-burn evaluation for one objective. With fewer
        than ``min_events`` in the long window the state is ``ok`` — an
        idle service is not in violation."""
        t = self._clock() if now is None else now
        with self._lock:
            ring = self._rings[objective]
            horizon_events = sum(
                g + b
                for _, g, b in ring._buckets  # noqa: SLF001 — same module
            )
        if horizon_events < self.min_events:
            return STATE_OK
        for long_w, short_w, threshold in self.page_rules:
            bl = self._burn(objective, long_w, t)
            bs = self._burn(objective, short_w, t)
            if bl is not None and bs is not None and bl > threshold and bs > threshold:
                return STATE_PAGE
        for long_w, short_w, threshold in self.warn_rules:
            bl = self._burn(objective, long_w, t)
            bs = self._burn(objective, short_w, t)
            if bl is not None and bs is not None and bl > threshold and bs > threshold:
                return STATE_WARN
        return STATE_OK

    # -- surfaces ----------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The ``/healthz`` block: per objective target/threshold, burn per
        window, state; plus the worst state overall."""
        t = self._clock() if now is None else now
        out: dict = {"objectives": {}, "state": STATE_OK}
        worst = STATE_OK
        for name, obj in self.objectives.items():
            state = self.state(name, now=t)
            if STATE_LEVEL[state] > STATE_LEVEL[worst]:
                worst = state
            out["objectives"][name] = dict(
                target=obj.target,
                threshold=obj.threshold,
                unit=obj.unit,
                events=self._events[name],
                burn=self.burn_rates(name, now=t),
                state=state,
            )
        out["state"] = worst
        return out

    def publish_metrics(
        self,
        reg: Optional[MetricsRegistry] = None,
        now: Optional[float] = None,
    ) -> None:
        """Mirror burn + state into gauges (``slo_burn_rate{objective,
        window}``, ``slo_state{objective}`` as 0/1/2) so the fleet
        ``/metrics`` scrape carries SLO posture without parsing healthz."""
        reg = reg or registry()
        t = self._clock() if now is None else now
        for name in self.objectives:
            for window, burn in self.burn_rates(name, now=t).items():
                if burn is not None:
                    reg.gauge(
                        "slo_burn_rate", objective=name, window=window
                    ).set(burn)
            reg.gauge("slo_state", objective=name).set(
                STATE_LEVEL[self.state(name, now=t)]
            )


def _window_name(window_s: float) -> str:
    if window_s % 3600 == 0:
        return f"{int(window_s // 3600)}h"
    if window_s % 60 == 0:
        return f"{int(window_s // 60)}m"
    return f"{int(window_s)}s"
