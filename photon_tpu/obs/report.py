"""Run-report finalizer: one schema-stable JSONL artifact per run.

Role parity: the reference GAME driver's single structured optimization
log per run (photon-client event/Event.scala PhotonOptimizationLogEvent) —
here generalized to the whole telemetry surface: trace spans (obs/trace),
registry metrics (obs/metrics), phase timers (utils/timed), the
coordinate-descent tracker, and the environment, serialized as one JSONL
file behind ``--telemetry-out`` on every CLI driver and emitted through
``EventEmitter`` as a ``PhotonOptimizationLogEvent`` payload.

Sync discipline: this module is the ONE place device-resident diagnostics
(RandomEffectTrackerStats arrays, OptimizeResult scalars) are read — once,
at finalize, after training finished. Nothing here runs inside the
dispatch hot loop, so ``CoordinateDescent.run(profile=False)`` stays
sync-free end to end with telemetry fully enabled.

Every line validates against :data:`TELEMETRY_SCHEMA` (checked in; tests
and the ci.sh telemetry smoke stage both enforce it), and every line
passes through ``sanitize_for_json`` so no NaN/Inf token ever reaches a
strict JSON parser.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
from typing import Any, Dict, List, Optional

from photon_tpu.utils import resources

# v2 (2026-08): histogram ``stats`` gained p50/p95/p99 keys (bounded
# deterministic reservoir, obs/metrics.py). Backward compatible for readers:
# ``stats`` was already typed as an open dict, no field was removed or
# renamed — v1 readers keep parsing v2 artifacts; only readers that REQUIRE
# percentiles need to check schema_version >= 2.
SCHEMA_VERSION = 2

_NONE = type(None)

# record type -> {field: allowed python types}. Exactly these fields, no
# more, no fewer — "schema-stable" means a reader written against this
# dict keeps parsing every future run at the same schema_version.
TELEMETRY_SCHEMA: Dict[str, Dict[str, tuple]] = {
    "meta": {
        "record": (str,),
        "schema_version": (int,),
        "run_id": (str,),
        "driver": (str,),
        "created_unix_s": (int, float),
    },
    "env": {
        "record": (str,),
        "jax_backend": (str,),
        "device_count": (int,),
        "process_index": (int,),
        "python": (str,),
        "env": (dict,),
    },
    "span": {
        "record": (str,),
        "name": (str,),
        "parent": (str, _NONE),
        "start_s": (int, float),
        "duration_s": (int, float),
        "thread": (str,),
    },
    "phase": {
        "record": (str,),
        "name": (str,),
        "duration_s": (int, float),
    },
    "metric": {
        "record": (str,),
        "metric": (str,),
        "type": (str,),
        "labels": (dict,),
        "value": (int, float, _NONE),
        "stats": (dict, _NONE),
    },
    "coordinate_descent": {
        "record": (str,),
        "label": (str,),
        "coordinate": (str,),
        "cd_iteration": (int,),
        "wall_s": (int, float, _NONE),
        "diagnostics": (dict,),
    },
}


def validate_record(rec: Any) -> None:
    """Raise ValueError unless ``rec`` is exactly one schema record."""
    if not isinstance(rec, dict):
        raise ValueError(f"telemetry record must be a dict, got {type(rec)}")
    kind = rec.get("record")
    fields = TELEMETRY_SCHEMA.get(kind)
    if fields is None:
        raise ValueError(f"unknown telemetry record type {kind!r}")
    missing = set(fields) - set(rec)
    extra = set(rec) - set(fields)
    if missing or extra:
        raise ValueError(
            f"{kind} record fields mismatch: missing={sorted(missing)} "
            f"extra={sorted(extra)}"
        )
    for field, types in fields.items():
        v = rec[field]
        if not isinstance(v, types) or (
            # bool is an int subclass; only "record"-typed str fields and
            # genuine numerics are allowed, never a stray bool-as-int.
            isinstance(v, bool) and bool not in types
        ):
            raise ValueError(
                f"{kind}.{field}: {type(v).__name__} not in "
                f"{tuple(t.__name__ for t in types)}"
            )


def _diagnostics_dict(diag: Any) -> Dict[str, Any]:
    """Serialize one tracker diagnostic — the single finalize-time read of
    device-resident stats. Objects expose ``diagnostics_dict()``
    (RandomEffectTrackerStats, OptimizeResult); anything else degrades to
    its repr so a new coordinate type never breaks report writing."""
    fn = getattr(diag, "diagnostics_dict", None)
    if fn is not None:
        return fn()
    return {"repr": repr(diag)}


def _publish_solve_cache(reg) -> None:
    """Snapshot the shared compiled-solver cache into the registry:
    lifetime traces/calls/hits totals plus per-trace-key trace counts (the
    bench's retrace breakdown, now a labeled metric)."""
    from photon_tpu.algorithm.solve_cache import default_cache

    cache = default_cache()
    stats = cache.stats
    reg.gauge("solve_cache_traces").set(stats.traces)
    reg.gauge("solve_cache_calls").set(stats.calls)
    reg.gauge("solve_cache_hits").set(stats.hits)
    reg.gauge("solve_cache_evictions").set(stats.evictions)
    reg.gauge("solve_cache_entries").set(cache.num_entries)
    per_key: Dict[str, int] = {}
    for key in stats.trace_keys:
        k = "/".join(str(p) for p in key)
        per_key[k] = per_key.get(k, 0) + 1
    for k, n in per_key.items():
        reg.gauge("solve_cache_traces_by_key", key=k).set(n)


def _publish_tracker(reg, label: str, tracker: Dict[str, list]) -> None:
    """Optimizer outcomes → registry (iters histogram + convergence-reason
    counters), read from the finalize-time diagnostics."""
    for cid, diags in tracker.items():
        for diag in diags:
            d = _diagnostics_dict(diag)
            if d.get("type") == "fixed_effect":
                reg.histogram(
                    "optimizer_iterations", coordinate=cid, label=label
                ).observe(d["iterations"])
                reg.counter(
                    "optimizer_convergence_total",
                    coordinate=cid, reason=d["reason"], label=label,
                ).inc()
            elif d.get("type") == "random_effect":
                reg.counter(
                    "re_entities_trained_total", coordinate=cid, label=label
                ).inc(d["entities"])
                reg.counter(
                    "re_entities_converged_total", coordinate=cid, label=label
                ).inc(d["converged"])
                reg.histogram(
                    "re_mean_iterations", coordinate=cid, label=label
                ).observe(d["mean_iterations"])


def environment_record() -> Dict[str, Any]:
    import jax

    return dict(
        record="env",
        jax_backend=jax.default_backend(),
        device_count=int(jax.device_count()),
        process_index=int(jax.process_index()),
        python=sys.version.split()[0],
        env={k: v for k, v in sorted(os.environ.items())
             if k.startswith(("PHOTON_TPU_", "JAX_PLATFORMS"))},
    )


def collect_run_records(
    driver: str,
    run_id: Optional[str] = None,
    trackers: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """Assemble the full record list: meta, env, phases, spans, metrics,
    coordinate-descent tracker rows. ``trackers`` entries are
    ``{"label", "tracker", "wall_times"}`` (one per trained config)."""
    from photon_tpu.evaluation.metrics_map import sanitize_for_json
    from photon_tpu.obs.metrics import registry
    from photon_tpu.obs.trace import get_spans, tracer
    from photon_tpu.utils.timed import Timed

    reg = registry()
    _publish_solve_cache(reg)

    records: List[Dict[str, Any]] = [
        dict(
            record="meta",
            schema_version=SCHEMA_VERSION,
            run_id=run_id or f"{driver}-{os.getpid()}",
            driver=driver,
            created_unix_s=tracer().epoch_unix_s,
        ),
        environment_record(),
    ]
    with Timed.records_lock():
        phases = dict(Timed.records)
    records.extend(
        dict(record="phase", name=name, duration_s=round(dur, 6))
        for name, dur in sorted(phases.items())
    )
    records.extend(s.as_dict() for s in get_spans())
    for entry in trackers or []:
        label = str(entry.get("label", ""))
        tracker = entry.get("tracker") or {}
        wall_times = entry.get("wall_times") or {}
        _publish_tracker(reg, label, tracker)
        for cid, diags in tracker.items():
            walls = wall_times.get(cid, [])
            for i, diag in enumerate(diags):
                records.append(
                    dict(
                        record="coordinate_descent",
                        label=label,
                        coordinate=cid,
                        cd_iteration=i,
                        wall_s=round(walls[i], 6) if i < len(walls) else None,
                        diagnostics=_diagnostics_dict(diag),
                    )
                )
    # Metrics last: tracker publication above lands in this snapshot.
    records.extend(reg.snapshot())
    records = [sanitize_for_json(r) for r in records]
    for rec in records:
        validate_record(rec)
    return records


_write_lock = threading.Lock()

# Budget enforcement drops record types in this order (cheapest loss
# first): spans are per-operation and unbounded under load, CD rows are
# per-iteration, phases are per-run. meta/env/metric records NEVER drop —
# they are the summary a size-capped report exists to preserve.
_DROP_ORDER = ("span", "coordinate_descent", "phase")


def _budget_lines(
    lines: List[str], kinds: List[str], max_bytes: int
) -> List[str]:
    """Trim serialized lines to ``max_bytes``, dropping droppable record
    kinds oldest-first. Returns the surviving lines (original order)."""
    total = sum(len(line) for line in lines)
    if total <= max_bytes:
        return lines
    keep = [True] * len(lines)
    dropped = 0
    for kind in _DROP_ORDER:
        if total <= max_bytes:
            break
        for i, k in enumerate(kinds):
            if k == kind and keep[i]:
                keep[i] = False
                total -= len(lines[i])
                dropped += 1
                if total <= max_bytes:
                    break
    if dropped:
        from photon_tpu.obs.metrics import registry

        registry().counter("telemetry_records_dropped_total").inc(dropped)
        logging.getLogger("photon_tpu").warning(
            "run report over its %d-byte budget: dropped %d oldest "
            "span/cd/phase records (summary records always kept)",
            max_bytes, dropped,
        )
    return [line for i, line in enumerate(lines) if keep[i]]


def write_run_report(
    path: str,
    records: List[Dict[str, Any]],
    max_bytes: Optional[int] = None,
) -> None:
    """Serialize records as JSONL (one validated, sanitized object per
    line). Parent directories are created; the write is atomic (tmp +
    rename), so a reader polling mid-soak never sees a torn file.

    ``max_bytes`` (default: ``PHOTON_TPU_TELEMETRY_MAX_BYTES`` env, else
    unbounded) is the rotation budget: the previous report rotates to
    ``<path>.1`` and, if the new snapshot alone exceeds the budget, the
    oldest span records drop first (then coordinate-descent rows, then
    phases) — meta/env/metric summary records are always kept, so a
    long soak degrades telemetry granularity, never observability.

    Telemetry sits at the bottom of the degradation priority: an OSError on
    the final write (disk full at finalize, say) drops the report with a
    warning and a ``telemetry_write_failures_total`` count instead of
    crashing the driver after training already succeeded. The partial tmp
    file is removed either way."""
    global _last_write_error
    if max_bytes is None:
        env = os.environ.get("PHOTON_TPU_TELEMETRY_MAX_BYTES")
        if env:
            max_bytes = int(env)
    guard = resources.DiskBudgetGuard("telemetry.write")
    lines = [json.dumps(rec, sort_keys=True) + "\n" for rec in records]
    with _write_lock:
        tmp = path + ".tmp"
        try:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            if max_bytes is not None and max_bytes > 0:
                kinds = [rec.get("record") for rec in records]
                lines = _budget_lines(lines, kinds, max_bytes)
                if os.path.exists(path):
                    try:
                        os.replace(path, path + ".1")
                    except OSError:
                        pass  # rotation is best-effort; the write is not
            with open(tmp, "w") as f:
                guard.check()  # ``enospc``/error rules for telemetry.write
                f.writelines(lines)
            os.replace(tmp, path)
            try:
                from photon_tpu.obs.metrics import registry

                registry().counter("telemetry_bytes_written_total").inc(
                    sum(len(line) for line in lines)
                )
            except Exception:
                pass
            _last_write_error = None
        except OSError as exc:
            guard.record(exc)
            guard.cleanup(tmp)
            try:
                from photon_tpu.obs.metrics import registry

                registry().counter("telemetry_write_failures_total").inc()
            except Exception:
                pass
            logging.getLogger("photon_tpu").warning(
                "dropping run report %s (%d records): write failed: %s",
                path, len(records), exc,
            )
            _last_write_error = f"{type(exc).__name__}: {exc}"


# Last run-report write failure (None after a successful write): the
# human-readable tail of the sink-health story the counters can't tell.
_last_write_error: Optional[str] = None


def telemetry_sink_health() -> Dict[str, Any]:
    """The ``/healthz`` telemetry-sink block: is the observability data
    itself healthy — bytes landed, records shed under the byte budget,
    write failures, and the most recent write error (telemetry sits at the
    bottom of the degradation priority, so "serving is fine but telemetry
    is dropping" must be visible SOMEWHERE other than the dropped data)."""
    from photon_tpu.obs.metrics import registry

    def _count(name: str) -> float:
        inst = registry().find(name)
        return float(inst.value) if inst is not None else 0.0

    return dict(
        bytes_written=_count("telemetry_bytes_written_total"),
        records_dropped=_count("telemetry_records_dropped_total"),
        write_failures=_count("telemetry_write_failures_total"),
        last_write_error=_last_write_error,
    )


def finalize_run_report(
    driver: str,
    path: Optional[str] = None,
    emitter=None,
    trackers: Optional[List[Dict[str, Any]]] = None,
    run_id: Optional[str] = None,
    max_bytes: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """The driver-exit hook: collect, write (when ``path``), and emit one
    ``PhotonOptimizationLogEvent`` carrying the records (listeners get the
    same payload the file holds)."""
    records = collect_run_records(driver, run_id=run_id, trackers=trackers)
    if path:
        write_run_report(path, records, max_bytes=max_bytes)
    if emitter is not None:
        from photon_tpu.utils.events import optimization_log_event

        emitter.emit(
            optimization_log_event(
                kind="run_telemetry",
                driver=driver,
                path=path,
                num_records=len(records),
                records=records,
            )
        )
    return records
