"""Online model-quality plane: streaming AUC/calibration per model version.

The SLO plane (obs/slo.py) answers "is serving healthy"; nothing in the
repo answered "is the MODEL getting better or worse in production". This
module closes that gap with a streaming evaluator over the (score, label)
pairs the feedback spool already joins: mergeable, windowed accumulators
keyed by ``(model_version, tenant, re_type)`` —

- fixed-bin score histograms per label class → online AUC whose error vs
  the exact ``evaluation/evaluators.py::auc_roc`` is bounded by bin width
  (records falling in the same bin are treated as ties, so the rank error
  per pair is at most one bin);
- logloss (logistic) or deviance (Poisson) keyed by task type;
- calibration bins (predicted mean vs observed mean) + ECE;
- label-delay distribution (labelTs − scoreTs) over fixed log buckets.

Everything is plain host-side float math — safe to call from serve
completion callbacks, and every accumulator merges associatively
(``merge(a, b) == accumulate(a ++ b)`` exactly, element-wise adds only),
which is what lets per-replica planes roll up in the fleet scrape the same
way every other per-replica instrument does: each replica publishes its
own ``quality_*`` series with its replica label, one cheap merge at scrape
(the Snap ML hierarchical-aggregation shape).

Windows rotate on a fixed wall-clock grid (``window_s``) and the plane
retains the last ``num_windows`` of them; reported numbers always come
from the retained-window merge, so a version that WAS bad and recovered
stops paging once the bad windows age out. Rotation is monotone under
clock skew: a clock that jumps backwards never reopens (or double-counts
into) an already-rotated window — observations clamp into the newest one.

The frozen-baseline lane is just a second key: the serving engine
re-scores labeled traffic on a pinned baseline generation and feeds those
pairs under the baseline's version key, so "lift" is the difference of two
MEASURED online AUCs over the same requests — never a modeled number.

SLO feed: per label observation the plane emits one good/bad event each
for the ``auc_drop`` and ``calibration_drift`` objectives (good = windowed
AUC within ``auc_drop_bound`` of the baseline's; good = windowed ECE under
``ece_bound``), into whatever SLOTracker the caller passes. Quality burn
then drives the SAME multi-window burn-rate machinery — and, through the
rollout watcher's ``--slo-gate``, the same abort/rollback/freeze actuation
path — as availability or latency burn.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

SLO_AUC_DROP = "auc_drop"
SLO_CALIBRATION = "calibration_drift"

# Label-delay histogram bucket upper bounds (seconds); the last bucket is
# open-ended. Log-spaced so sub-second joins and hour-late labels both
# resolve; mergeable by construction (fixed bounds, counts add).
DELAY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0, 1800.0,
)


def task_name(task) -> str:
    """TaskType (or string) → the quality plane's task family:
    ``logistic`` | ``poisson`` | ``linear``. Unknown tasks score as
    ``linear`` (identity link, no calibration claim)."""
    name = str(getattr(task, "value", task) or "").upper()
    if "LOGISTIC" in name or "HINGE" in name:
        return "logistic"
    if "POISSON" in name:
        return "poisson"
    return "linear"


def predict(score: float, task: str) -> float:
    """Raw serving score (margin) → mean prediction under the task's
    inverse link. Serving scores are link-scale (``score_with_offset``),
    so AUC binning and calibration both need the mean scale."""
    s = float(score)
    if task == "logistic":
        if s >= 0:
            return 1.0 / (1.0 + math.exp(-s))
        e = math.exp(s)
        return e / (1.0 + e)
    if task == "poisson":
        return math.exp(min(s, 50.0))
    return s


@dataclasses.dataclass
class QualityConfig:
    """Knobs for one quality plane. ``score_bins`` bounds the online-AUC
    error (ties within a bin); ``window_s`` × ``num_windows`` is the
    horizon every reported number covers."""

    task: str = "logistic"
    score_bins: int = 64
    calibration_bins: int = 10
    window_s: float = 30.0
    num_windows: int = 4
    # Below this many (score, label) pairs in the retained windows a key
    # reports no AUC/ECE (and its SLO events default to good) — an idle
    # version is not in violation.
    min_events: int = 20
    baseline_version: Optional[str] = None
    # SLO event bars: good iff windowed AUC ≥ baseline AUC − auc_drop_bound
    # and windowed ECE ≤ ece_bound.
    auc_drop_bound: float = 0.05
    ece_bound: float = 0.15


class QualityAccumulator:
    """One key's mergeable quality state. Every field is a sum (or a
    fixed-size vector of sums), so ``merge`` is element-wise addition and
    exactly associative/commutative — the property the merge-equivalence
    test pins and the fleet rollup relies on."""

    __slots__ = (
        "score_bins", "calibration_bins", "pos", "neg", "count", "weight",
        "loss_sum", "calib_w", "calib_p", "calib_y", "delay_counts",
        "delay_sum",
    )

    def __init__(self, score_bins: int = 64, calibration_bins: int = 10):
        self.score_bins = int(score_bins)
        self.calibration_bins = int(calibration_bins)
        self.pos = [0.0] * self.score_bins  # weighted counts, label == 1
        self.neg = [0.0] * self.score_bins  # weighted counts, label == 0
        self.count = 0
        self.weight = 0.0
        self.loss_sum = 0.0  # weighted logloss or Poisson deviance
        self.calib_w = [0.0] * self.calibration_bins
        self.calib_p = [0.0] * self.calibration_bins  # Σ w·prediction
        self.calib_y = [0.0] * self.calibration_bins  # Σ w·label
        self.delay_counts = [0] * (len(DELAY_BUCKETS_S) + 1)
        self.delay_sum = 0.0

    # -- accumulate --------------------------------------------------------

    def _bin(self, pred: float, bins: int) -> int:
        # Predictions clamp into [0, 1] for binning (logistic predictions
        # already live there; other tasks rank fine after clamping because
        # AUC only needs a monotone transform).
        p = min(1.0, max(0.0, pred))
        return min(bins - 1, int(p * bins))

    def observe(
        self,
        pred: float,
        label: float,
        task: str = "logistic",
        weight: float = 1.0,
        delay_s: Optional[float] = None,
    ) -> None:
        w = float(weight)
        y = float(label)
        self.count += 1
        self.weight += w
        b = self._bin(pred, self.score_bins)
        if y > 0.5:
            self.pos[b] += w
        else:
            self.neg[b] += w
        c = self._bin(pred, self.calibration_bins)
        self.calib_w[c] += w
        self.calib_p[c] += w * min(1.0, max(0.0, pred))
        self.calib_y[c] += w * y
        eps = 1e-7
        if task == "poisson":
            # Poisson deviance: 2·(y·log(y/μ) − (y − μ)), y·log(y/μ)=0 at y=0.
            mu = max(pred, eps)
            term = y * math.log(y / mu) if y > 0 else 0.0
            self.loss_sum += w * 2.0 * (term - (y - mu))
        elif task == "linear":
            # Squared error — the identity-link prediction is unbounded and
            # the label is real-valued, so the logloss clamp below would
            # destroy both.
            self.loss_sum += w * (pred - y) ** 2
        else:
            p = min(1.0 - eps, max(eps, pred))
            self.loss_sum += w * -(y * math.log(p) + (1.0 - y) * math.log(1.0 - p))
        if delay_s is not None:
            d = max(0.0, float(delay_s))
            self.delay_sum += d
            for i, bound in enumerate(DELAY_BUCKETS_S):
                if d <= bound:
                    self.delay_counts[i] += 1
                    break
            else:
                self.delay_counts[-1] += 1

    def merge(self, other: "QualityAccumulator") -> "QualityAccumulator":
        if (other.score_bins != self.score_bins
                or other.calibration_bins != self.calibration_bins):
            raise ValueError("cannot merge accumulators with different bins")
        self.count += other.count
        self.weight += other.weight
        self.loss_sum += other.loss_sum
        self.delay_sum += other.delay_sum
        for i in range(self.score_bins):
            self.pos[i] += other.pos[i]
            self.neg[i] += other.neg[i]
        for i in range(self.calibration_bins):
            self.calib_w[i] += other.calib_w[i]
            self.calib_p[i] += other.calib_p[i]
            self.calib_y[i] += other.calib_y[i]
        for i in range(len(self.delay_counts)):
            self.delay_counts[i] += other.delay_counts[i]
        return self

    # -- derived metrics ---------------------------------------------------

    def auc(self) -> Optional[float]:
        """Histogram AUC: P(score_pos > score_neg) + ½·P(tie), where "tie"
        means "same bin". Identical to the exact ``auc_roc`` when no
        opposite-class pair shares a bin; otherwise off by at most the
        within-bin tie mass — |err| ≤ ½·Σ_b (pos_b·neg_b)/(P·N) ≤ ½ · max
        bin co-occupancy, which shrinks as 1/score_bins for continuous
        score distributions. None for single-class windows (undefined)."""
        p_tot = sum(self.pos)
        n_tot = sum(self.neg)
        if p_tot <= 0.0 or n_tot <= 0.0:
            return None
        cum_neg = 0.0
        s = 0.0
        for b in range(self.score_bins):
            s += self.pos[b] * (cum_neg + 0.5 * self.neg[b])
            cum_neg += self.neg[b]
        return s / (p_tot * n_tot)

    def ece(self) -> Optional[float]:
        """Expected calibration error: Σ_b (w_b/W)·|ȳ_b − p̄_b|."""
        if self.weight <= 0.0:
            return None
        out = 0.0
        for b in range(self.calibration_bins):
            w = self.calib_w[b]
            if w <= 0.0:
                continue
            out += (w / self.weight) * abs(
                self.calib_y[b] / w - self.calib_p[b] / w
            )
        return out

    def mean_loss(self) -> Optional[float]:
        return self.loss_sum / self.weight if self.weight > 0.0 else None

    def delay_percentile(self, q: float) -> Optional[float]:
        """Bucket-resolution percentile of the label delay (upper bound of
        the bucket the q-th observation falls in; the open tail reports the
        running mean as its best available estimate)."""
        total = sum(self.delay_counts)
        if total <= 0:
            return None
        rank = q * total
        seen = 0
        for i, c in enumerate(self.delay_counts):
            seen += c
            if seen >= rank:
                if i < len(DELAY_BUCKETS_S):
                    return DELAY_BUCKETS_S[i]
                break
        n_delay = total
        return self.delay_sum / n_delay

    def snapshot(self, task: str = "logistic") -> dict:
        out = dict(
            count=self.count,
            weight=self.weight,
            auc=self.auc(),
            ece=self.ece(),
            label_delay_p50_s=self.delay_percentile(0.5),
            label_delay_p95_s=self.delay_percentile(0.95),
        )
        loss = self.mean_loss()
        out["deviance" if task == "poisson" else "logloss"] = loss
        return out


def _key(
    model_version: Optional[str],
    tenant: Optional[str],
    re_type: Optional[str],
) -> Tuple[str, str, str]:
    import os

    v = os.path.basename(str(model_version or "unknown").rstrip("/"))
    return (v, str(tenant or ""), str(re_type or ""))


class QualityPlane:
    """Keyed, windowed quality accumulators + the registry/SLO surfaces.

    Thread-safe; all math host-side. One plane lives on the serving engine
    (fed by the feedback-spool label join and the frozen-baseline lane) and
    one on each streaming updater (fed by the deterministic holdout
    split)."""

    def __init__(
        self,
        config: Optional[QualityConfig] = None,
        clock=time.time,
    ):
        self.config = config or QualityConfig()
        self._clock = clock
        self._lock = threading.Lock()
        # window grid index -> {key: accumulator}; ordered oldest-first.
        self._windows: "OrderedDict[int, Dict[Tuple[str, str, str], QualityAccumulator]]" = OrderedDict()
        self._max_idx: Optional[int] = None  # monotone rotation floor

    # -- windowing ---------------------------------------------------------

    def _window_locked(self, now: float) -> Dict:
        idx = int(now // max(self.config.window_s, 1e-6))
        if self._max_idx is None or idx > self._max_idx:
            self._max_idx = idx
            self._windows[idx] = {}
            while len(self._windows) > max(1, int(self.config.num_windows)):
                self._windows.popitem(last=False)
        # Clock skew (idx < _max_idx): clamp into the newest window — never
        # reopen an aged-out one, never count an event twice.
        return self._windows[self._max_idx]

    def _acc_for(self, window: Dict, key) -> QualityAccumulator:
        acc = window.get(key)
        if acc is None:
            acc = QualityAccumulator(
                self.config.score_bins, self.config.calibration_bins
            )
            window[key] = acc
        return acc

    def window_totals(self) -> Dict[Tuple[str, str, str], QualityAccumulator]:
        """Retained windows merged into one accumulator per key — the
        number every surface (metrics, SLO events, CLI) reports."""
        with self._lock:
            out: Dict[Tuple[str, str, str], QualityAccumulator] = {}
            for window in self._windows.values():
                for key, acc in window.items():
                    tot = out.get(key)
                    if tot is None:
                        tot = QualityAccumulator(
                            acc.score_bins, acc.calibration_bins
                        )
                        out[key] = tot
                    tot.merge(acc)
            return out

    # -- feed --------------------------------------------------------------

    def observe(
        self,
        score: float,
        label: float,
        model_version: Optional[str] = None,
        tenant: Optional[str] = None,
        re_type: Optional[str] = None,
        ts: Optional[float] = None,
        label_ts: Optional[float] = None,
        weight: float = 1.0,
        trace_id: Optional[str] = None,
        slo=None,
        now: Optional[float] = None,
    ) -> None:
        """One joined (score, label) pair. ``slo`` (an SLOTracker) receives
        the per-event ``auc_drop``/``calibration_drift`` good/bad feed —
        skipped for the baseline lane itself (the baseline decaying is the
        measurement, not a violation)."""
        from photon_tpu.obs.metrics import registry

        cfg = self.config
        t = self._clock() if now is None else now
        key = _key(model_version, tenant, re_type)
        pred = predict(score, cfg.task)
        delay = None
        if ts is not None and label_ts is not None:
            delay = max(0.0, float(label_ts) - float(ts))
        with self._lock:
            window = self._window_locked(t)
            self._acc_for(window, key).observe(
                pred, label, task=cfg.task, weight=weight, delay_s=delay
            )
        labels = dict(
            model_version=key[0], tenant=key[1], re_type=key[2]
        )
        reg = registry()
        reg.counter("quality_observations_total", **labels).inc()
        if delay is not None:
            reg.histogram(
                "quality_label_delay_s", **labels
            ).observe(delay, trace_id=trace_id)
        if slo is not None and key[0] != (cfg.baseline_version or ""):
            self._record_slo(slo, key)

    def _record_slo(self, slo, key: Tuple[str, str, str]) -> None:
        """One good/bad event per objective for this observation. Both
        default to good below ``min_events`` — a cold window is not a
        violation, and the burn only starts once the windowed estimate is
        statistically meaningful."""
        cfg = self.config
        totals = self.window_totals()
        acc = totals.get(key)
        good_auc = True
        good_ece = True
        if acc is not None and acc.count >= cfg.min_events:
            auc = acc.auc()
            base_auc = None
            if cfg.baseline_version:
                base = totals.get(
                    (cfg.baseline_version, key[1], key[2])
                )
                if base is not None and base.count >= cfg.min_events:
                    base_auc = base.auc()
            if auc is not None and base_auc is not None:
                good_auc = auc >= base_auc - cfg.auc_drop_bound
            ece = acc.ece()
            if ece is not None:
                good_ece = ece <= cfg.ece_bound
        slo.record_event(SLO_AUC_DROP, good_auc)
        slo.record_event(SLO_CALIBRATION, good_ece)

    # -- surfaces ----------------------------------------------------------

    def set_baseline(self, model_version: Optional[str]) -> None:
        import os

        self.config.baseline_version = (
            os.path.basename(str(model_version).rstrip("/"))
            if model_version else None
        )

    def publish(self, reg=None) -> None:
        """Mirror windowed per-key quality into gauges so the ``/metrics``
        scrape (and through it the fleet merge and the OTLP metrics export)
        carries model quality alongside every operational series."""
        from photon_tpu.obs.metrics import registry

        reg = reg or registry()
        cfg = self.config
        totals = self.window_totals()
        loss_name = (
            "quality_deviance" if cfg.task == "poisson" else "quality_logloss"
        )
        for key, acc in totals.items():
            if acc.count < cfg.min_events:
                continue
            labels = dict(
                model_version=key[0], tenant=key[1], re_type=key[2]
            )
            auc = acc.auc()
            if auc is not None:
                reg.gauge("quality_auc", **labels).set(auc)
            ece = acc.ece()
            if ece is not None:
                reg.gauge("quality_ece", **labels).set(ece)
            loss = acc.mean_loss()
            if loss is not None:
                reg.gauge(loss_name, **labels).set(loss)
            if cfg.baseline_version and key[0] != cfg.baseline_version:
                base = totals.get((cfg.baseline_version, key[1], key[2]))
                if (base is not None and base.count >= cfg.min_events
                        and auc is not None):
                    base_auc = base.auc()
                    if base_auc is not None:
                        reg.gauge("quality_auc_lift", **labels).set(
                            auc - base_auc
                        )

    def snapshot(self) -> dict:
        """The ``stats()``/healthz quality block: per-key windowed metrics
        plus lift vs the baseline lane (measured, same horizon)."""
        cfg = self.config
        totals = self.window_totals()
        versions: List[dict] = []
        for key in sorted(totals):
            acc = totals[key]
            entry = dict(
                model_version=key[0], tenant=key[1], re_type=key[2],
                **acc.snapshot(cfg.task),
            )
            if cfg.baseline_version and key[0] != cfg.baseline_version:
                base = totals.get((cfg.baseline_version, key[1], key[2]))
                auc = acc.auc()
                base_auc = base.auc() if base is not None else None
                if auc is not None and base_auc is not None:
                    entry["auc_lift"] = auc - base_auc
            versions.append(entry)
        return dict(
            task=cfg.task,
            baseline=cfg.baseline_version,
            window_s=cfg.window_s,
            num_windows=cfg.num_windows,
            versions=versions,
        )
