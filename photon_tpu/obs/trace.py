"""Hierarchical trace spans: host-wall attribution for one training run.

The span tree is the run-report's answer to "where did this run spend its
time" — the hierarchical wall-clock attribution Snap ML (arxiv 1803.06333)
and the pjit/TPUv4 scaling work (arxiv 2204.06514) use to find the next
bottleneck: data path vs. solver vs. compile, per coordinate and per CD
pass, in one tree instead of four subsystems' private logs.

Contract (the sync-free dispatch rule): spans measure HOST wall only —
``time.monotonic`` around whatever the ``with`` body does. A span around a
jitted dispatch under ``CoordinateDescent.run(profile=False)`` therefore
times enqueue cost, never device execution, and introduces zero
``block_until_ready`` host syncs (tests/test_solve_cache.py pins this).

Nesting is thread-local by default: a span opened inside another span on
the same thread becomes its child (path ``parent/child``). Work handed to
another thread — the ingest pipeline's stage threads — passes the parent
path EXPLICITLY (``span(name, parent=path)``), so the tree stays connected
across threads without any global ambient state leaking between runs.

Cross-PROCESS nesting rides a W3C-traceparent-style ``TraceContext``
``(trace_id, parent_span_id, sampled)``: the frontend mints one per
request, every IPC frame carries it (``trace`` field), and each receiving
process opens REMOTE-CHILD spans — spans stamped with
``trace_id/span_id/parent_span_id`` so the trees from the HTTP worker, the
scorer, and each fleet replica reassemble into one request tree. Trace
identity lives OUTSIDE the run-report schema: ``SpanRecord.as_dict()`` is
unchanged (report.py's strict schema still validates); the wire/dump form
is ``as_trace_dict()``. Untraced spans (no context) pay nothing new.

The tail-based ``FlightRecorder`` buffers traced spans per trace id and, at
request completion, keeps the full tree ONLY for requests that are slow
(latency above its own streaming p99), errored, degraded, or explicitly
forced by a client-sent ``traceparent`` header — the "what just went wrong"
ring the ``/v1/traces`` endpoint and ``photon-tpu-obs`` dump.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from photon_tpu.obs.metrics import Histogram, _label_key

SEP = "/"

# Serving soaks record spans per micro-batch indefinitely; an unbounded
# list is a slow memory leak. The collector keeps the NEWEST max_spans
# (deque ring), counting what it sheds — the run report's byte budget
# (obs/report.py) is the second line of defense.
DEFAULT_MAX_SPANS = int(os.environ.get("PHOTON_TPU_TRACE_MAX_SPANS", 100_000))

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """What crosses a process boundary: which request (``trace_id``), which
    caller span to nest under (``parent_span_id``), and whether anyone is
    recording (``sampled``). ``forced`` marks traces the CLIENT asked for
    via an explicit ``traceparent`` header — the flight recorder keeps
    those unconditionally instead of tail-sampling them."""

    trace_id: str
    parent_span_id: Optional[str] = None
    sampled: bool = True
    forced: bool = False

    def child(self, span_id: str) -> "TraceContext":
        """The context to hand DOWNSTREAM from a span: same trace, the
        given span as the new parent."""
        return TraceContext(self.trace_id, span_id, self.sampled, self.forced)

    # -- wire forms --------------------------------------------------------

    def to_dict(self) -> dict:
        return dict(
            traceId=self.trace_id,
            parentSpanId=self.parent_span_id,
            sampled=bool(self.sampled),
            forced=bool(self.forced),
        )

    @classmethod
    def from_dict(cls, obj) -> Optional["TraceContext"]:
        if not isinstance(obj, dict):
            return None
        tid = obj.get("traceId")
        if not isinstance(tid, str) or not tid:
            return None
        psid = obj.get("parentSpanId")
        return cls(
            trace_id=tid,
            parent_span_id=psid if isinstance(psid, str) and psid else None,
            sampled=bool(obj.get("sampled", True)),
            forced=bool(obj.get("forced", False)),
        )

    def to_traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.parent_span_id or '0' * 16}-{flags}"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Parse an incoming ``traceparent`` header. An explicit header is a
        request to SEE the trace, so it arrives ``forced``."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        _, tid, psid, flags = m.groups()
        if tid == "0" * 32:
            return None
        return cls(
            trace_id=tid,
            parent_span_id=None if psid == "0" * 16 else psid,
            sampled=bool(int(flags, 16) & 1),
            forced=True,
        )


def mint_context(sampled: bool = True, forced: bool = False) -> TraceContext:
    """A fresh root context (no parent span yet): what the frontend mints
    when a request arrives without a ``traceparent`` header."""
    return TraceContext(new_trace_id(), None, sampled, forced)


@dataclasses.dataclass(frozen=True)
class SpanRecord:
    """One finished span. ``start_s`` is relative to the tracer epoch
    (reset at driver entry), so the report is stable across machines.

    The trace-identity fields (``trace_id/span_id/parent_span_id/pid``) are
    set only on spans recorded under a sampled TraceContext; they are
    deliberately NOT part of ``as_dict()`` so the run-report schema
    (obs/report.py, exact-field validation) is untouched — cross-process
    dumps use ``as_trace_dict()`` instead."""

    name: str  # full hierarchical path, e.g. "cd/iter3/per-user/solve"
    parent: Optional[str]  # full path of the enclosing span (None = root)
    start_s: float
    duration_s: float
    thread: str
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    pid: Optional[int] = None

    def as_dict(self) -> dict:
        return dict(
            record="span",
            name=self.name,
            parent=self.parent,
            start_s=round(self.start_s, 6),
            duration_s=round(self.duration_s, 6),
            thread=self.thread,
        )

    def as_trace_dict(self) -> dict:
        """The cross-process dump form: everything ``as_dict`` has plus
        trace identity, keyed for JSON wire use."""
        return dict(
            name=self.name,
            parent=self.parent,
            start_s=round(self.start_s, 6),
            duration_s=round(self.duration_s, 6),
            thread=self.thread,
            traceId=self.trace_id,
            spanId=self.span_id,
            parentSpanId=self.parent_span_id,
            pid=self.pid,
        )


class Tracer:
    """Thread-safe span collector. One process-global instance backs the
    module-level helpers; tests may build private ones."""

    def __init__(self, max_spans: Optional[int] = None):
        self._lock = threading.Lock()
        self.max_spans = DEFAULT_MAX_SPANS if max_spans is None else max_spans
        self._spans: deque = deque(
            maxlen=self.max_spans if self.max_spans > 0 else None
        )
        self.dropped_spans = 0
        self._local = threading.local()
        self._epoch = time.monotonic()
        self.epoch_unix_s = time.time()
        self._sinks: List[Callable[[SpanRecord], None]] = []

    # -- thread-local nesting stack ---------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _tstack(self) -> List[Optional[Tuple[str, str, bool]]]:
        """Parallel to ``_stack``: per open span, its (trace_id, span_id,
        forced) when it was opened under a sampled context, else None."""
        ts = getattr(self._local, "tstack", None)
        if ts is None:
            ts = self._local.tstack = []
        return ts

    def current_path(self) -> Optional[str]:
        """Full path of the innermost open span on THIS thread (None at
        top level). Capture it before handing work to another thread and
        pass it as ``parent=`` there."""
        stack = self._stack()
        return stack[-1] if stack else None

    # -- cross-process context ---------------------------------------------

    @contextmanager
    def attach_context(self, ctx: Optional[TraceContext]) -> Iterator[None]:
        """Install an incoming (deserialized) context as this thread's
        ambient trace: spans opened in the body become remote children of
        the caller's span. Restores the previous attachment on exit."""
        prev = getattr(self._local, "attached", None)
        self._local.attached = ctx
        try:
            yield
        finally:
            self._local.attached = prev

    def _innermost_traced(self) -> Optional[Tuple[str, str, bool]]:
        for entry in reversed(self._tstack()):
            if entry is not None:
                return entry
        return None

    def current_context(self) -> Optional[TraceContext]:
        """The context to hand DOWNSTREAM from this thread right now: the
        innermost open traced span if any, else the attached incoming
        context, else None (nothing is tracing)."""
        entry = self._innermost_traced()
        if entry is not None:
            tid, sid, forced = entry
            return TraceContext(tid, sid, True, forced)
        return getattr(self._local, "attached", None)

    # Alias named for symmetry with attach_context: "extract" is what a
    # sender calls immediately before serializing onto the wire.
    extract_context = current_context

    def _effective_context(
        self, context: Optional[TraceContext]
    ) -> Optional[TraceContext]:
        if context is not None:
            return context
        return self.current_context()

    # -- recording ---------------------------------------------------------

    @contextmanager
    def span(
        self,
        name: str,
        parent: Optional[str] = None,
        context: Optional[TraceContext] = None,
    ) -> Iterator[str]:
        """Time the body; record one SpanRecord on exit (exceptions
        included — a failed phase still shows its wall). Yields the full
        path so callers can hand it to worker threads.

        With a sampled ``context`` (explicit, ambient from an enclosing
        traced span, or attached via ``attach_context``) the span also gets
        trace identity: a fresh span id, parented on the innermost open
        traced span or the context's remote parent."""
        base = parent if parent is not None else self.current_path()
        path = f"{base}{SEP}{name}" if base else name
        ctx = self._effective_context(context)
        tentry: Optional[Tuple[str, str, bool]] = None
        psid: Optional[str] = None
        if ctx is not None and ctx.sampled:
            inner = self._innermost_traced()
            psid = inner[1] if inner is not None else ctx.parent_span_id
            tentry = (ctx.trace_id, new_span_id(), ctx.forced)
        stack = self._stack()
        tstack = self._tstack()
        stack.append(path)
        tstack.append(tentry)
        t0 = time.monotonic()
        try:
            yield path
        finally:
            dt = time.monotonic() - t0
            if stack and stack[-1] == path:
                stack.pop()
                if tstack:
                    tstack.pop()
            self._append(
                SpanRecord(
                    path, base, t0 - self._epoch, dt,
                    threading.current_thread().name,
                    trace_id=tentry[0] if tentry else None,
                    span_id=tentry[1] if tentry else None,
                    parent_span_id=psid if tentry else None,
                    pid=os.getpid() if tentry else None,
                )
            )

    def record(
        self,
        name: str,
        duration_s: float,
        parent: Optional[str] = None,
        start_s: Optional[float] = None,
        context: Optional[TraceContext] = None,
        span_id: Optional[str] = None,
    ) -> SpanRecord:
        """Record an externally-timed span (e.g. a generator whose lifetime
        was measured by its own try/finally, or a request whose completion
        lands on a callback thread). ``context``/``span_id`` give it trace
        identity: pre-mint the span id at dispatch time when downstream
        work must reference this span as parent BEFORE it completes.

        ``parent=""`` pins the span at the process root: completion
        callbacks run on whatever thread the engine flushes from, and a
        request-hop span must not inherit that thread's open span stack."""
        base = (parent if parent is not None else self.current_path()) or None
        path = f"{base}{SEP}{name}" if base else name
        if start_s is None:
            start_s = time.monotonic() - self._epoch - duration_s
        traced = context is not None and context.sampled
        rec = SpanRecord(
            path, base, start_s, duration_s,
            threading.current_thread().name,
            trace_id=context.trace_id if traced else None,
            span_id=(span_id or new_span_id()) if traced else None,
            parent_span_id=context.parent_span_id if traced else None,
            pid=os.getpid() if traced else None,
        )
        self._append(rec)
        return rec

    def add_sink(self, sink: Callable[[SpanRecord], None]) -> None:
        """Register a callable invoked (outside the tracer lock) for every
        TRACED span recorded — how the flight recorder collects per-request
        trees without the tracer knowing about it. Untraced spans skip the
        sinks entirely, keeping the training hot path unchanged."""
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink: Callable[[SpanRecord], None]) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    def _append(self, rec: SpanRecord) -> None:
        with self._lock:
            if (
                self._spans.maxlen is not None
                and len(self._spans) == self._spans.maxlen
            ):
                self.dropped_spans += 1  # ring full: deque sheds the oldest
            self._spans.append(rec)
            sinks = list(self._sinks) if rec.trace_id is not None else ()
        for sink in sinks:
            try:
                sink(rec)
            except Exception:
                pass  # a broken sink must never fail the traced work

    # -- introspection / lifecycle ----------------------------------------

    def spans(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        """New run: drop finished spans and restart the epoch. Open spans
        on other threads finish into the new run (they cannot be
        retroactively unwound); drivers reset at entry, before any spans
        open."""
        with self._lock:
            self._spans.clear()
            self.dropped_spans = 0
            self._epoch = time.monotonic()
            self.epoch_unix_s = time.time()


class FlightRecorder:
    """Tail-based keeper of full span trees for the requests worth looking
    at: slow (above this recorder's own streaming p99), errored, degraded
    (FE-only / breaker-open / pin-fallback), or client-forced.

    Registered as a tracer sink, it buffers traced spans per trace id in a
    bounded open table; ``finish(trace_id, ...)`` closes a request and
    decides keep vs. discard. Kept trees land in a bounded ring dumped by
    ``/v1/traces``. Everything is host-side dict/list work — no device
    interaction, so the sync-free dispatch rule holds with the recorder on.
    """

    DEFAULT_CAPACITY = int(os.environ.get("PHOTON_TPU_FLIGHT_CAPACITY", 128))
    MAX_SPANS_PER_TRACE = 512
    P99_REFRESH_EVERY = 32

    def __init__(
        self,
        capacity: Optional[int] = None,
        open_cap: int = 2048,
        min_latency_samples: int = 100,
    ):
        self._lock = threading.Lock()
        self.capacity = self.DEFAULT_CAPACITY if capacity is None else capacity
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._open: "OrderedDict[str, List[SpanRecord]]" = OrderedDict()
        self.open_cap = open_cap
        self.min_latency_samples = min_latency_samples
        self._lat = Histogram("flight_latency_s", _label_key({}))
        self._p99_cache: Optional[float] = None
        self._since_refresh = 0
        self.kept_total = 0
        self.discarded_total = 0
        self.open_evicted_total = 0
        self.span_overflow_total = 0
        self.ring_dropped_total = 0
        self.keep_all = os.environ.get("PHOTON_TPU_TRACE_KEEP_ALL") == "1"

    # -- tracer sink -------------------------------------------------------

    def on_span(self, rec: SpanRecord) -> None:
        tid = rec.trace_id
        if tid is None:
            return
        with self._lock:
            buf = self._open.get(tid)
            if buf is None:
                if len(self._open) >= self.open_cap:
                    # A trace whose finish() never came (caller died):
                    # evict the oldest wholesale rather than grow forever.
                    self._open.popitem(last=False)
                    self.open_evicted_total += 1
                buf = self._open[tid] = []
            if len(buf) >= self.MAX_SPANS_PER_TRACE:
                self.span_overflow_total += 1
                return
            buf.append(rec)

    # -- request completion ------------------------------------------------

    def _slow_threshold(self) -> Optional[float]:
        if self._lat.count < self.min_latency_samples:
            return None
        self._since_refresh += 1
        if self._p99_cache is None or (
            self._since_refresh >= self.P99_REFRESH_EVERY
        ):
            self._since_refresh = 0
            self._p99_cache = self._lat.percentiles((0.99,))["p99"]
        return self._p99_cache

    def finish(
        self,
        trace_id: str,
        latency_s: Optional[float] = None,
        error: Optional[str] = None,
        degraded: bool = False,
        forced: bool = False,
        meta: Optional[dict] = None,
    ) -> Optional[str]:
        """Close one request's trace: returns the keep reason
        (``forced/error/degraded/slow``) or None if discarded. The slow
        threshold is this recorder's own p99 so it self-calibrates to the
        workload without a config knob."""
        with self._lock:
            spans = self._open.pop(trace_id, [])
        threshold = None
        if latency_s is not None:
            threshold = self._slow_threshold()
            self._lat.observe(latency_s)
        reason = None
        if forced or self.keep_all:
            reason = "forced"
        elif error is not None:
            reason = "error"
        elif degraded:
            reason = "degraded"
        elif (
            latency_s is not None
            and threshold is not None
            and latency_s > threshold
        ):
            reason = "slow"
        if reason is None:
            with self._lock:
                self.discarded_total += 1
            return None
        entry = dict(
            traceId=trace_id,
            reason=reason,
            latencySeconds=latency_s,
            error=error,
            degraded=bool(degraded),
            pid=os.getpid(),
            unixTs=time.time(),
            meta=meta or {},
            spans=[s.as_trace_dict() for s in spans],
        )
        with self._lock:
            # The ring sheds its OLDEST kept tree when full; count the
            # shed so sustained forced-keep traffic (every tree kept) is
            # visible as overflow instead of silently rotating away.
            if (
                self._ring.maxlen is not None
                and len(self._ring) >= self._ring.maxlen
            ):
                self.ring_dropped_total += 1
            self._ring.append(entry)
            self.kept_total += 1
        return reason

    # -- introspection / lifecycle ----------------------------------------

    def traces(self, limit: Optional[int] = None) -> List[dict]:
        """Kept trees, oldest first (the ring order); ``limit`` keeps the
        NEWEST n."""
        with self._lock:
            out = list(self._ring)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def stats(self) -> dict:
        with self._lock:
            return dict(
                kept=self.kept_total,
                discarded=self.discarded_total,
                open=len(self._open),
                open_evicted=self.open_evicted_total,
                span_overflow=self.span_overflow_total,
                ring_dropped=self.ring_dropped_total,
                capacity=self.capacity,
                latency_samples=self._lat.count,
                slow_threshold_s=self._p99_cache,
            )

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open.clear()
            self.kept_total = 0
            self.discarded_total = 0
            self.open_evicted_total = 0
            self.span_overflow_total = 0
            self.ring_dropped_total = 0
            self._lat = Histogram("flight_latency_s", _label_key({}))
            self._p99_cache = None
            self._since_refresh = 0


def merge_trace_dumps(entries: List[dict]) -> List[dict]:
    """Merge flight-recorder dump entries from MULTIPLE processes into one
    entry per trace id: each hop's process kept its own spans for the same
    request, and the fleet ``/v1/traces`` answer should read as one tree.
    Spans concatenate (deduped by span id), ``pids`` is the sorted set of
    processes that contributed, latency is the max observed hop latency,
    and the first entry seen supplies the keep reason. Order of first
    appearance is preserved."""
    by_id: "OrderedDict[str, dict]" = OrderedDict()
    for e in entries:
        tid = e.get("traceId")
        if tid is None:
            continue
        cur = by_id.get(tid)
        if cur is None:
            cur = by_id[tid] = dict(e)
            cur["spans"] = list(e.get("spans") or [])
        else:
            cur["spans"].extend(e.get("spans") or [])
            if cur.get("error") is None and e.get("error") is not None:
                cur["error"] = e.get("error")
            cur["degraded"] = bool(cur.get("degraded")) or bool(
                e.get("degraded")
            )
            lats = [
                v
                for v in (cur.get("latencySeconds"), e.get("latencySeconds"))
                if v is not None
            ]
            cur["latencySeconds"] = max(lats) if lats else None
    out = []
    for cur in by_id.values():
        seen = set()
        spans = []
        for s in cur["spans"]:
            sid = s.get("spanId")
            if sid is not None:
                if sid in seen:
                    continue
                seen.add(sid)
            spans.append(s)
        cur["spans"] = spans
        cur["pids"] = sorted(
            {s.get("pid") for s in spans if s.get("pid") is not None}
        )
        out.append(cur)
    return out


_TRACER = Tracer()
_FLIGHT = FlightRecorder()
_TRACER.add_sink(_FLIGHT.on_span)


def tracer() -> Tracer:
    """The process-global tracer every subsystem records into."""
    return _TRACER


def flight_recorder() -> FlightRecorder:
    """The process-global tail-based recorder behind ``/v1/traces``."""
    return _FLIGHT


@contextmanager
def span(
    name: str,
    parent: Optional[str] = None,
    context: Optional[TraceContext] = None,
) -> Iterator[str]:
    with _TRACER.span(name, parent=parent, context=context) as path:
        yield path


def record_span(
    name: str,
    duration_s: float,
    parent: Optional[str] = None,
    start_s: Optional[float] = None,
    context: Optional[TraceContext] = None,
    span_id: Optional[str] = None,
) -> SpanRecord:
    return _TRACER.record(
        name, duration_s, parent=parent, start_s=start_s,
        context=context, span_id=span_id,
    )


def current_span_path() -> Optional[str]:
    return _TRACER.current_path()


def attach_context(ctx: Optional[TraceContext]):
    return _TRACER.attach_context(ctx)


def extract_context() -> Optional[TraceContext]:
    return _TRACER.current_context()


def get_spans() -> List[SpanRecord]:
    return _TRACER.spans()


def reset_tracer() -> None:
    _TRACER.reset()


def reset_flight_recorder() -> None:
    _FLIGHT.reset()
