"""Hierarchical trace spans: host-wall attribution for one training run.

The span tree is the run-report's answer to "where did this run spend its
time" — the hierarchical wall-clock attribution Snap ML (arxiv 1803.06333)
and the pjit/TPUv4 scaling work (arxiv 2204.06514) use to find the next
bottleneck: data path vs. solver vs. compile, per coordinate and per CD
pass, in one tree instead of four subsystems' private logs.

Contract (the sync-free dispatch rule): spans measure HOST wall only —
``time.monotonic`` around whatever the ``with`` body does. A span around a
jitted dispatch under ``CoordinateDescent.run(profile=False)`` therefore
times enqueue cost, never device execution, and introduces zero
``block_until_ready`` host syncs (tests/test_solve_cache.py pins this).

Nesting is thread-local by default: a span opened inside another span on
the same thread becomes its child (path ``parent/child``). Work handed to
another thread — the ingest pipeline's stage threads — passes the parent
path EXPLICITLY (``span(name, parent=path)``), so the tree stays connected
across threads without any global ambient state leaking between runs.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Iterator, List, Optional

SEP = "/"

# Serving soaks record spans per micro-batch indefinitely; an unbounded
# list is a slow memory leak. The collector keeps the NEWEST max_spans
# (deque ring), counting what it sheds — the run report's byte budget
# (obs/report.py) is the second line of defense.
DEFAULT_MAX_SPANS = int(os.environ.get("PHOTON_TPU_TRACE_MAX_SPANS", 100_000))


@dataclasses.dataclass(frozen=True)
class SpanRecord:
    """One finished span. ``start_s`` is relative to the tracer epoch
    (reset at driver entry), so the report is stable across machines."""

    name: str  # full hierarchical path, e.g. "cd/iter3/per-user/solve"
    parent: Optional[str]  # full path of the enclosing span (None = root)
    start_s: float
    duration_s: float
    thread: str

    def as_dict(self) -> dict:
        return dict(
            record="span",
            name=self.name,
            parent=self.parent,
            start_s=round(self.start_s, 6),
            duration_s=round(self.duration_s, 6),
            thread=self.thread,
        )


class Tracer:
    """Thread-safe span collector. One process-global instance backs the
    module-level helpers; tests may build private ones."""

    def __init__(self, max_spans: Optional[int] = None):
        self._lock = threading.Lock()
        self.max_spans = DEFAULT_MAX_SPANS if max_spans is None else max_spans
        self._spans: deque = deque(
            maxlen=self.max_spans if self.max_spans > 0 else None
        )
        self.dropped_spans = 0
        self._local = threading.local()
        self._epoch = time.monotonic()
        self.epoch_unix_s = time.time()

    # -- thread-local nesting stack ---------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_path(self) -> Optional[str]:
        """Full path of the innermost open span on THIS thread (None at
        top level). Capture it before handing work to another thread and
        pass it as ``parent=`` there."""
        stack = self._stack()
        return stack[-1] if stack else None

    # -- recording ---------------------------------------------------------

    @contextmanager
    def span(self, name: str, parent: Optional[str] = None) -> Iterator[str]:
        """Time the body; record one SpanRecord on exit (exceptions
        included — a failed phase still shows its wall). Yields the full
        path so callers can hand it to worker threads."""
        base = parent if parent is not None else self.current_path()
        path = f"{base}{SEP}{name}" if base else name
        stack = self._stack()
        stack.append(path)
        t0 = time.monotonic()
        try:
            yield path
        finally:
            dt = time.monotonic() - t0
            if stack and stack[-1] == path:
                stack.pop()
            self._append(
                SpanRecord(path, base, t0 - self._epoch, dt,
                           threading.current_thread().name)
            )

    def record(
        self,
        name: str,
        duration_s: float,
        parent: Optional[str] = None,
        start_s: Optional[float] = None,
    ) -> SpanRecord:
        """Record an externally-timed span (e.g. a generator whose lifetime
        was measured by its own try/finally)."""
        base = parent if parent is not None else self.current_path()
        path = f"{base}{SEP}{name}" if base else name
        if start_s is None:
            start_s = time.monotonic() - self._epoch - duration_s
        rec = SpanRecord(path, base, start_s, duration_s,
                         threading.current_thread().name)
        self._append(rec)
        return rec

    def _append(self, rec: SpanRecord) -> None:
        with self._lock:
            if (
                self._spans.maxlen is not None
                and len(self._spans) == self._spans.maxlen
            ):
                self.dropped_spans += 1  # ring full: deque sheds the oldest
            self._spans.append(rec)

    # -- introspection / lifecycle ----------------------------------------

    def spans(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        """New run: drop finished spans and restart the epoch. Open spans
        on other threads finish into the new run (they cannot be
        retroactively unwound); drivers reset at entry, before any spans
        open."""
        with self._lock:
            self._spans.clear()
            self.dropped_spans = 0
            self._epoch = time.monotonic()
            self.epoch_unix_s = time.time()


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-global tracer every subsystem records into."""
    return _TRACER


@contextmanager
def span(name: str, parent: Optional[str] = None) -> Iterator[str]:
    with _TRACER.span(name, parent=parent) as path:
        yield path


def record_span(
    name: str,
    duration_s: float,
    parent: Optional[str] = None,
    start_s: Optional[float] = None,
) -> SpanRecord:
    return _TRACER.record(name, duration_s, parent=parent, start_s=start_s)


def current_span_path() -> Optional[str]:
    return _TRACER.current_path()


def get_spans() -> List[SpanRecord]:
    return _TRACER.spans()


def reset_tracer() -> None:
    _TRACER.reset()
