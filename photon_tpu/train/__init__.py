"""Training-side orchestration above the estimators: incremental
generation-over-generation updates (train/incremental.py). The estimators
stay pure "fit a model" machinery; this package owns the lifecycle glue —
parent loading, changed-entity selection, merge, manifest, validation gate.
"""
