"""Incremental generation updates: re-train only what changed, gate the rest.

The continuous-rollout training half (ROADMAP "close the train→serve loop"):
instead of re-fitting the whole population every refresh, an incremental
update

1. loads the PARENT generation (whatever ``LATEST`` points to) as the warm
   start, with the publish root's index maps / entity indexes so slot
   assignments stay stable across generations;
2. trains on the DELTA batch only — the entities present in it are exactly
   the "data changed" set, and the active-set machinery gives per-entity
   convergence inside the passes;
3. MERGES: changed entities take their freshly trained rows, unchanged
   entities keep the parent's coefficients verbatim (not "approximately
   preserved through the solver" — copied), new entities append;
4. records a generation manifest (per-file sha256, parent id, holdout
   metrics) and runs the validation gate; only a passing generation moves
   the fsync'd LATEST pointer that serving watches.

An entity quarantined (DIVERGED) in generation g keeps its warm-start row
there by the solver's quarantine contract; when its data shows up in the
g+1 delta it is simply a changed entity again — it re-enters the active set
and trains from the warm start that survived the manifest round trip
(tests/test_rollout.py exercises exactly this heal).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    ProjectedRandomEffectModel,
    RandomEffectModel,
)

logger = logging.getLogger(__name__)


def changed_entity_mask(batch, re_type: str, num_entities: int) -> np.ndarray:
    """(E,) bool — entities with at least one row in the delta batch. This
    IS the "data changed" set: the delta reader only carries rows whose
    data moved since the parent generation."""
    mask = np.zeros(int(num_entities), bool)
    eids = np.asarray(batch.entity_ids[re_type]).astype(np.int64)
    valid = (eids >= 0) & (eids < num_entities)
    mask[eids[valid]] = True
    return mask


def _dense_re(model) -> RandomEffectModel:
    if isinstance(model, ProjectedRandomEffectModel):
        return model.to_dense()
    return model


def merge_random_effect(
    parent: Optional[RandomEffectModel],
    trained: RandomEffectModel,
    changed: np.ndarray,
) -> RandomEffectModel:
    """Row-level merge of one RE coordinate: changed rows from ``trained``,
    everything else verbatim from ``parent``. Both models are sized to the
    SAME entity space (the parent loads against the already-grown entity
    index, so new entities exist as absent rows there)."""
    trained = _dense_re(trained)
    t_coefs = np.asarray(trained.coefficients, np.float32)
    E, d = t_coefs.shape
    changed = np.asarray(changed, bool)
    if changed.shape[0] != E:
        raise ValueError(
            f"changed mask has {changed.shape[0]} entities, model has {E}"
        )
    if parent is None:
        present = changed.copy()
        coefs = np.where(changed[:, None], t_coefs, 0.0).astype(np.float32)
        return RandomEffectModel(
            coefs, trained.re_type, trained.feature_shard, trained.task,
            None, present_entities=present,
        )
    parent = _dense_re(parent)
    p_coefs = np.asarray(parent.coefficients, np.float32)
    p_present = getattr(parent, "present_entities", None)
    p_present = (
        np.ones((p_coefs.shape[0],), bool)
        if p_present is None
        else np.asarray(p_present, bool)
    )
    if p_coefs.shape[1] != d:
        raise ValueError(
            f"parent dim {p_coefs.shape[1]} != trained dim {d} for RE "
            f"coordinate {trained.re_type!r}"
        )
    coefs = np.zeros((E, d), np.float32)
    present = np.zeros((E,), bool)
    k = min(E, p_coefs.shape[0])
    coefs[:k] = p_coefs[:k]
    present[:k] = p_present[:k]
    coefs[changed] = t_coefs[changed]
    present |= changed
    variances = None
    if trained.variances is not None and parent.variances is not None:
        variances = np.zeros((E, d), np.float32)
        variances[:k] = np.asarray(parent.variances, np.float32)[:k]
        variances[changed] = np.asarray(trained.variances, np.float32)[changed]
    return RandomEffectModel(
        coefs, trained.re_type, trained.feature_shard, trained.task,
        variances, present_entities=present,
    )


def merge_models(
    parent: Optional[GameModel],
    trained: GameModel,
    changed_masks: Dict[str, np.ndarray],
) -> GameModel:
    """Generation merge: fixed effects take the (warm-started) retrain;
    random effects merge row-wise per ``changed_masks[re_type]``."""
    merged: Dict[str, object] = {}
    for cid, sub in trained.models.items():
        if isinstance(sub, FixedEffectModel):
            merged[cid] = sub
            continue
        p_sub = parent.get(cid) if parent is not None else None
        dense = _dense_re(sub)
        changed = changed_masks.get(dense.re_type)
        if changed is None:
            changed = np.ones((np.asarray(dense.coefficients).shape[0],), bool)
        merged[cid] = merge_random_effect(p_sub, dense, changed)
    return GameModel(merged)


def compute_holdout_metrics(model: GameModel, batch, suite) -> Dict[str, float]:
    """Holdout-metric record for the generation manifest — scored with the
    MERGED model (what would serve), not the raw retrain.

    Fault site ``model.bad_holdout`` simulates a refresh that silently got
    worse: each metric is pushed past any sane regression tolerance in its
    own worse direction, so the gate's holdout pass must refuse the
    generation."""
    from photon_tpu.utils import faults

    metrics = suite.evaluate_model(model, batch)
    rule = faults.injector().fire("model.bad_holdout")
    if rule is not None:
        from photon_tpu.evaluation.suite import EvaluatorSpec

        bad = {}
        for name, v in metrics.items():
            try:
                higher_better = EvaluatorSpec.parse(name).better()(1.0, 0.0)
            except Exception:  # noqa: BLE001 — unknown metric: degrade anyway
                higher_better = True
            bad[name] = v - 0.5 if higher_better else v * 10.0 + 1.0
        logger.warning(
            "fault model.bad_holdout: recorded metrics degraded %s -> %s",
            metrics, bad,
        )
        metrics = bad
    return metrics


def read_dead_letters(paths: Sequence[str]) -> List[dict]:
    """Parse pipeline dead-letter sidecar JSONL files (io/pipeline.py writes
    one record per dropped chunk). The incremental driver records these in
    the generation manifest so the skipped rows are targeted — visibly, not
    silently lost — by the next refresh."""
    out: List[dict] = []
    for path in paths:
        if not path or not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    logger.warning("unparseable dead-letter line in %s", path)
    return out


@dataclasses.dataclass
class IncrementalResult:
    generation: str
    model_dir: str
    published: bool
    gate_reason: Optional[str]
    holdout_metrics: Dict[str, float]
    changed_entities: Dict[str, int]
    parent: Optional[str]
    is_delta: bool = False


def incremental_update(
    publish_root: str,
    batch,
    index_maps: Dict,
    entity_indexes: Dict,
    task,
    coordinate_configs: Sequence,
    update_sequence: Sequence[str],
    valid_batch=None,
    evaluation_suite=None,
    generation: Optional[str] = None,
    locked_coordinates: Sequence[str] = (),
    num_iterations: int = 1,
    metric_tolerance: float = 0.02,
    norm_drift_bound: float = 10.0,
    sparsity_threshold: float = 0.0,
    re_convergence_tol: float = 1e-4,
    re_device_budget_mb: Optional[float] = None,
    re_spill_dir: Optional[str] = None,
    re_spill_member: Optional[str] = None,
    dead_letters: Optional[List[dict]] = None,
    publish: bool = True,
    emit_delta: bool = False,
    extra_manifest: Optional[dict] = None,
    serialize_publish: bool = False,
    optimization_config=None,
) -> IncrementalResult:
    """One incremental generation, end to end: warm-start train on the
    delta ``batch`` → merge over the parent → save → manifest → gate →
    (maybe) publish. ``entity_indexes`` must already contain the delta's
    interning — the parent loads against it so every array is sized to the
    grown entity space.

    ``sparsity_threshold`` defaults to 0 (exact round trip): an incremental
    chain re-loads its own output as the next warm start, and thresholding
    would decay coefficients a little every generation.

    ``emit_delta=True`` persists the generation as a per-entity DELTA layer
    over the parent (only changed rows written; the resolved chain is
    bit-identical to a full publish) — the streaming updater's micro-
    generation artifact. Falls back to a full publish when there is no
    parent or nothing qualifies for a layer. ``extra_manifest`` merges extra
    keys into the generation manifest (e.g. the stream consume cursor).

    ``optimization_config`` (a :class:`GameOptimizationConfig`) overrides
    the coordinate configs' own regularization grid with ONE explicit
    point — the experiment plane trains each GP-proposed candidate at
    exactly its proposed λ instead of sweeping the base grid.

    ``serialize_publish=True`` runs the save→manifest→gate tail under the
    publish root's :func:`~photon_tpu.io.model_io.publish_lock` and REBASES
    onto whatever ``LATEST`` is at publish time: when a concurrent publisher
    (a sibling updater shard) flipped the pointer since this cycle resolved
    its warm-start parent, the changed rows are re-merged over the live
    resolved model so the sibling's rows ride through instead of being
    clobbered by this cycle's stale view. The changed rows themselves are
    untouched by the rebase — per-entity solves depend only on the entity's
    own warm start and data, so disjoint-entity publishers commute."""
    import contextlib

    from photon_tpu.cli.game_serving import resolve_model_dir
    from photon_tpu.estimators.game_estimator import GameEstimator
    from photon_tpu.io.model_io import (
        allocate_generation,
        gate_and_publish,
        load_resolved_game_model,
        publish_lock,
        save_delta_model,
        save_game_model,
        write_generation_manifest,
    )

    parent_dir = resolve_model_dir(publish_root)
    has_parent = parent_dir != publish_root and os.path.isdir(parent_dir)
    parent_name = os.path.basename(parent_dir.rstrip("/")) if has_parent else None
    parent = None
    if has_parent:
        # Delta-aware: a streaming parent can itself be a delta layer; the
        # warm start must be the RESOLVED model, not the layer's few rows.
        parent = load_resolved_game_model(
            parent_dir, index_maps, entity_indexes, to_device=True,
            publish_root=publish_root,
        )

    num_entities = {k: len(v) for k, v in entity_indexes.items()}
    changed_masks = {
        re_type: changed_entity_mask(batch, re_type, E)
        for re_type, E in num_entities.items()
        if re_type in batch.entity_ids
    }
    changed_counts = {k: int(v.sum()) for k, v in changed_masks.items()}
    logger.info(
        "incremental update: parent=%s changed entities=%s",
        parent_name, changed_counts,
    )

    estimator = GameEstimator(
        task=task,
        coordinate_configs=list(coordinate_configs),
        num_iterations=num_iterations,
        num_entities=num_entities,
        locked_coordinates=list(locked_coordinates),
        warm_start_model=parent,
        ignore_threshold_for_new_models=parent is not None,
        re_active_set=True,
        re_convergence_tol=re_convergence_tol,
        re_device_budget_mb=re_device_budget_mb,
        re_spill_dir=re_spill_dir,
        re_spill_member=re_spill_member,
    )
    results = estimator.fit(
        batch,
        validation_batch=valid_batch,
        evaluation_suite=(
            evaluation_suite if valid_batch is not None else None
        ),
        initial_model=parent,
        optimization_configs=(
            [optimization_config] if optimization_config is not None else None
        ),
    )
    best = (
        estimator.select_best(results, evaluation_suite)
        if evaluation_suite is not None and valid_batch is not None
        else results[-1]
    )
    merged = merge_models(parent, best.model, changed_masks)

    holdout: Dict[str, float] = {}
    if valid_batch is not None and evaluation_suite is not None:
        holdout = compute_holdout_metrics(merged, valid_batch, evaluation_suite)

    lock = (
        publish_lock(publish_root) if serialize_publish
        else contextlib.nullcontext()
    )
    with lock:
        publish_parent = parent_name
        if serialize_publish:
            live_dir = resolve_model_dir(publish_root)
            live_ok = live_dir != publish_root and os.path.isdir(live_dir)
            live_name = (
                os.path.basename(live_dir.rstrip("/")) if live_ok else None
            )
            if live_ok and live_name != parent_name:
                # Rebase: a sibling publisher flipped LATEST while this
                # cycle trained. Re-merge the changed rows over the LIVE
                # resolved model so the sibling's rows ride through
                # verbatim; this cycle's trained rows are unaffected.
                live_parent = load_resolved_game_model(
                    live_dir, index_maps, entity_indexes, to_device=True,
                    publish_root=publish_root,
                )
                merged = merge_models(live_parent, best.model, changed_masks)
                publish_parent = live_name
        # Allocation is flock-serialized: concurrent updaters (batch +
        # streaming, or two streaming shard workers) must never claim the
        # same generation id.
        generation = generation or allocate_generation(publish_root)
        model_dir = os.path.join(publish_root, generation)
        is_delta = False
        if emit_delta and publish_parent is not None:
            # Every RE coordinate needs a mask; a coordinate whose re_type
            # the delta batch never mentioned changed nowhere (merge kept
            # the parent rows verbatim), so it contributes no rows to the
            # layer.
            save_masks = dict(changed_masks)
            for sub in merged.models.values():
                if isinstance(sub, RandomEffectModel):
                    save_masks.setdefault(
                        sub.re_type,
                        np.zeros(
                            (np.asarray(sub.coefficients).shape[0],), bool
                        ),
                    )
            fe_cids = [
                cid for cid, sub in merged.models.items()
                if isinstance(sub, FixedEffectModel)
            ]
            include_fixed = any(c not in locked_coordinates for c in fe_cids)
            try:
                save_delta_model(
                    merged, save_masks, model_dir, index_maps, entity_indexes,
                    base=publish_parent,
                    sparsity_threshold=sparsity_threshold,
                    include_fixed=include_fixed,
                )
                is_delta = True
            except ValueError as exc:
                logger.info(
                    "delta layer not emittable (%s); publishing full", exc
                )
        if not is_delta:
            save_game_model(
                merged, model_dir, index_maps, entity_indexes,
                sparsity_threshold=sparsity_threshold,
            )
        # Entity indexes grew with the delta's new entities; persist them
        # BEFORE the pointer can move so a reloading server resolves every
        # slot the new generation references. (Interning is append-only:
        # existing slots are stable, so the running server's copy stays
        # valid too.)
        for shard, imap in index_maps.items():
            imap.save(os.path.join(publish_root, f"index-map-{shard}.json"))
        for re_type, eidx in entity_indexes.items():
            eidx.save(
                os.path.join(publish_root, f"entity-index-{re_type}.json")
            )
        extra = {"changedEntities": changed_counts}
        if dead_letters:
            extra["deadLetterChunks"] = dead_letters
        if extra_manifest:
            extra.update(extra_manifest)
        write_generation_manifest(
            model_dir, parent=publish_parent, holdout_metrics=holdout,
            extra=extra,
        )
        if publish:
            gate = gate_and_publish(
                publish_root, generation,
                metric_tolerance=metric_tolerance,
                norm_drift_bound=norm_drift_bound,
            )
            published, reason = gate.ok, gate.reason
        else:
            published, reason = False, "publish_disabled"
    return IncrementalResult(
        generation=generation,
        model_dir=model_dir,
        published=published,
        gate_reason=reason,
        holdout_metrics=holdout,
        changed_entities=changed_counts,
        parent=publish_parent,
        is_delta=is_delta,
    )
