"""Jitted GLMix training-step builders — the SPMD programs the drivers run.

This is the TPU replacement for the reference's per-iteration Spark
choreography (SURVEY.md §3.2): one compiled program trains the fixed-effect
coordinate over the data-sharded batch (gradient psums inserted by XLA), and
one compiled program per entity block trains all its random-effect models
(vmapped solves over the entity-sharded block). Sharding layout:

  batch arrays   (n, ...)  → P('data', ...)      gradient reductions on ICI
  coefficients   (d,)      → P() or P('feature') (replicated / TP-sharded)
  entity blocks  (E, ...)  → P('data', ...)      independent per-entity solves
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.data.random_effect import EntityBlock
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import OptimizerConfig
from photon_tpu.optim.margin_lbfgs import minimize_lbfgs_margin
from photon_tpu.optim.newton import minimize_newton
from photon_tpu.parallel.mesh import dp_axes

Array = jax.Array


def glmix_train_step(
    fixed_objective: GLMObjective,
    re_objective: GLMObjective,
    fe_config: OptimizerConfig,
    re_config: OptimizerConfig,
    re_solver: str = "newton",
):
    """One full GLMix coordinate-descent pass as a single jittable function:

      (w_fixed, re_coefs, fe_batch, re_block, base_offset) →
          (w_fixed', re_coefs', scores)

    Residual exchange between the two coordinates happens inside the program
    (flat array arithmetic — reference CoordinateDescent.scala:441-446 role).
    Designed to be jitted with shardings: fe_batch rows on 'data', re_block
    entities on 'data', coefficients replicated.

    Also returns exact work counters for throughput accounting:
    ``fe_evals`` (fixed-effect X passes — the margin solver's cost unit;
    O(n) line-search trials are excluded) and ``re_sample_visits``
    (Σ_e passes_e × n_e over entities).

    Smooth objectives only: L1/elastic-net training routes through the
    coordinate-descent path (OWL-QN); see photon_tpu.algorithm.

    ``re_solver`` picks the per-entity solver: ``"newton"`` (default —
    batched damped Newton with Cholesky, 3-5 iterations at 2 X-passes each,
    no inner loops; optim/newton.py) or ``"lbfgs"`` (margin-space L-BFGS,
    useful when d_re is too large to form per-entity Hessians).
    """
    if fixed_objective.l1_weight > 0.0 or re_objective.l1_weight > 0.0:
        raise ValueError(
            "glmix_train_step solves smooth objectives (L-BFGS); use the "
            "coordinate-descent path for L1/elastic-net (OWL-QN routing)"
        )
    if re_solver not in ("newton", "lbfgs"):
        raise ValueError(f"unknown re_solver {re_solver!r}")

    def step(
        w_fixed: Array,
        re_coefs: Array,  # (E, d_re)
        fe_batch: LabeledBatch,
        re_block: EntityBlock,
        re_features_flat: Array,  # (n, d_re) per-sample RE shard features
        re_entity_ids: Array,  # (n,)
        fe_l2: Array = None,  # traced λ overriding the FE objective's L2
        re_l2: Array = None,  # traced λ overriding the RE objective's L2
    ):
        # The l2 overrides are the hyperparameter-sweep hook: vmapping this
        # step over (fe_l2, re_l2) lanes trains a whole λ grid in ONE program
        # sharing each X pass (SURVEY.md §2.7.5 — parallel tuning, absent in
        # the reference's sequential loop GameEstimator.scala:364-382).
        # --- RE scores on the flat batch (gather by entity) ---
        def re_scores_of(coefs):
            valid = re_entity_ids >= 0
            w = coefs[jnp.maximum(re_entity_ids, 0)]
            return jnp.where(valid, jnp.sum(re_features_flat * w, axis=-1), 0.0)

        # --- fixed effect trains against RE residuals ---
        # Margin-space L-BFGS: 2 X-passes/iter, O(n) line-search trials.
        fe_res = minimize_lbfgs_margin(
            fixed_objective,
            fe_batch.add_scores_to_offsets(re_scores_of(re_coefs)),
            w_fixed,
            fe_config,
            l2_override=fe_l2,
        )
        w_fixed_new = fe_res.w

        # --- fixed scores as residual offsets for the RE solves ---
        fe_scores = fe_batch.margins(w_fixed_new)  # includes base offsets
        offs = re_block.gather_offsets(fe_scores)

        def solve_one(feat, lab, wt, off, w_init):
            lb = LabeledBatch(lab, feat, off, wt)
            if re_solver == "newton":
                res = minimize_newton(
                    re_objective, lb, w_init, re_config, l2_override=re_l2
                )
            else:
                res = minimize_lbfgs_margin(
                    re_objective, lb, w_init, re_config, l2_override=re_l2
                )
            return res.w, res.evals

        w_init = re_coefs[re_block.entity_idx]
        w_new, re_evals = jax.vmap(solve_one)(
            re_block.features, re_block.label, re_block.weight, offs, w_init,
        )
        # Entities under the active_lower_bound filter keep their existing
        # model (EntityBlock.train_mask contract, data/random_effect.py).
        w_new = jnp.where(re_block.train_mask[:, None], w_new, w_init)
        re_coefs_new = re_coefs.at[re_block.entity_idx].set(w_new)
        re_sample_visits = jnp.sum(
            re_evals * jnp.sum((re_block.weight > 0).astype(jnp.int32), axis=1)
        )

        total_scores = fe_scores + re_scores_of(re_coefs_new)
        return w_fixed_new, re_coefs_new, total_scores, fe_res.evals, re_sample_visits

    return step


def glmix_sharded_train_step(
    mesh: Mesh,
    fixed_objective: GLMObjective,
    re_objective: GLMObjective,
    fe_config: OptimizerConfig,
    re_config: OptimizerConfig,
    re_solver: str = "newton",
):
    """glmix_train_step jitted over a mesh, plus a placement function that
    device_puts the inputs with the intended shardings (the program the
    driver's dryrun_multichip compiles and runs).

    Returns (jitted_step, place) where place(w_fixed, re_coefs, fe_batch,
    re_block, re_features_flat, re_entity_ids) returns the sharded args.
    """
    import dataclasses

    # The fused Pallas path assumes single-device data; on a sharded batch a
    # pallas_call would gather X to one device and defeat the DP layout, so
    # the distributed program always takes the XLA (psum-inserted) path.
    fixed_objective = dataclasses.replace(fixed_objective, use_pallas=False)
    re_objective = dataclasses.replace(re_objective, use_pallas=False)
    step = glmix_train_step(
        fixed_objective, re_objective, fe_config, re_config, re_solver
    )

    dp = dp_axes(mesh)  # ('slice','data') on multi-slice meshes
    repl = NamedSharding(mesh, P())
    rows = NamedSharding(mesh, P(dp))
    rows2d = NamedSharding(mesh, P(dp, None))
    rows3d = NamedSharding(mesh, P(dp, None, None))

    def place(w_fixed, re_coefs, fe_batch, re_block, re_features_flat, re_entity_ids):
        put = jax.device_put
        feats = fe_batch.features
        if isinstance(feats, SparseFeatures):
            # A transpose plan (flat column-sorted nnz order) is only valid
            # for the unsharded layout — rebuild without it; the sharded
            # gradient uses the scatter-add path per shard.
            feats = SparseFeatures(
                put(feats.indices, rows2d), put(feats.values, rows2d), feats.dim
            )
        else:
            feats = put(feats, rows2d)
        fe = LabeledBatch(
            label=put(fe_batch.label, rows),
            features=feats,
            offset=put(fe_batch.offset, rows),
            weight=put(fe_batch.weight, rows),
            uid=None,
        )
        rb = EntityBlock(
            entity_idx=put(re_block.entity_idx, rows),
            features=put(re_block.features, rows3d),
            label=put(re_block.label, rows2d),
            weight=put(re_block.weight, rows2d),
            sample_index=put(re_block.sample_index, rows2d),
            train_mask=put(re_block.train_mask, rows),
        )
        return (
            put(w_fixed, repl),
            put(re_coefs, repl),
            fe,
            rb,
            put(re_features_flat, rows2d),
            put(re_entity_ids, rows),
        )

    return jax.jit(step, out_shardings=(repl, repl, rows, repl, repl)), place


def stack_shard_blocks(shard_blocks, pad_entities: Optional[int] = None):
    """Stack one EntityBlock per shard into a (S, ...)-leading EntityBlock
    for :func:`game_entity_sharded_train_step`.

    All shards must share (n_max, d); entity counts are padded up to
    ``pad_entities`` (default: the max across shards) with -1/zero padding
    rows — the same filler discipline shape bucketing uses, so the fused
    program sees one uniform geometry regardless of ring imbalance.
    """
    import numpy as np

    E_pad = pad_entities or max(int(b.entity_idx.shape[0]) for b in shard_blocks)
    n_max = int(shard_blocks[0].features.shape[1])
    d = int(shard_blocks[0].features.shape[2])

    def pad(b):
        if any(sb.col_map is not None for sb in shard_blocks):
            raise ValueError("stack_shard_blocks: projected blocks unsupported")
        if b.features.shape[1:] != (n_max, d):
            raise ValueError(
                f"stack_shard_blocks: shard geometry mismatch "
                f"{b.features.shape[1:]} vs {(n_max, d)}"
            )
        k = E_pad - int(b.entity_idx.shape[0])
        return EntityBlock(
            entity_idx=np.pad(np.asarray(b.entity_idx), (0, k), constant_values=-1),
            features=np.pad(np.asarray(b.features), ((0, k), (0, 0), (0, 0))),
            label=np.pad(np.asarray(b.label), ((0, k), (0, 0))),
            weight=np.pad(np.asarray(b.weight), ((0, k), (0, 0))),
            sample_index=np.pad(
                np.asarray(b.sample_index), ((0, k), (0, 0)), constant_values=-1
            ),
            train_mask=np.pad(np.asarray(b.train_mask), (0, k)),
        )

    padded = [pad(b) for b in shard_blocks]
    return EntityBlock(
        entity_idx=jnp.stack([jnp.asarray(b.entity_idx) for b in padded]),
        features=jnp.stack([jnp.asarray(b.features) for b in padded]),
        label=jnp.stack([jnp.asarray(b.label) for b in padded]),
        weight=jnp.stack([jnp.asarray(b.weight) for b in padded]),
        sample_index=jnp.stack([jnp.asarray(b.sample_index) for b in padded]),
        train_mask=jnp.stack([jnp.asarray(b.train_mask) for b in padded]),
    )


def game_entity_sharded_train_step(
    mesh: Mesh,
    fixed_objective: GLMObjective,
    re_objective: GLMObjective,
    fe_config: OptimizerConfig,
    re_config: OptimizerConfig,
    re_solver: str = "newton",
):
    """The whole-program entity-sharded GAME pass: RE coefficient store and
    entity blocks carry a leading SHARD axis partitioned over the mesh's
    data axis, so every entity's block solve runs on the device that owns
    its shard (parallel/entity_shard.py assignment) and the coefficient
    table is genuinely distributed — (S, E_s, d) with each (E_s, d) slab
    resident on one device, not replicated.

    Cross-device exchange happens exactly where the coordinate path merges
    scores/residuals: the flat-batch RE score gather reads the sharded
    table through a reshape (XLA inserts the one all-gather), and the FE
    residual gather by ``sample_index`` pulls the rows-sharded margins to
    each shard's blocks. The per-shard coefficient scatter is the same
    drop-mode discipline as the single-device program, vmapped over the
    shard axis — it updates each slab in place, preserving the sharding.

    Inputs (see ``place``):
      w_fixed           (d,)                 replicated
      re_coefs          (S, E_s, d_re)       P('data') — shard slabs
      fe_batch          rows                 P('data')
      re_block          (S, E_b, n_max, …)   P('data') — stack_shard_blocks
      re_features_flat  (n, d_re)            P('data')
      re_shard_ids      (n,)                 P('data') — owning shard / -1
      re_local_ids      (n,)                 P('data') — local entity index

    Uniform geometry required: every shard's block must share
    (E_b, n_max, d) — pad through :func:`stack_shard_blocks`. Projected
    blocks are unsupported (col_map is content-defined per block).
    """
    import dataclasses

    fixed_objective = dataclasses.replace(fixed_objective, use_pallas=False)
    re_objective = dataclasses.replace(re_objective, use_pallas=False)
    if fixed_objective.l1_weight > 0.0 or re_objective.l1_weight > 0.0:
        raise ValueError(
            "game_entity_sharded_train_step solves smooth objectives; use "
            "the coordinate-descent path for L1/elastic-net"
        )
    if re_solver not in ("newton", "lbfgs"):
        raise ValueError(f"unknown re_solver {re_solver!r}")

    def step(
        w_fixed: Array,
        re_coefs: Array,  # (S, E_s, d_re)
        fe_batch: LabeledBatch,
        re_block: EntityBlock,  # leading shard axis
        re_features_flat: Array,  # (n, d_re)
        re_shard_ids: Array,  # (n,)
        re_local_ids: Array,  # (n,)
    ):
        S, E_s = re_coefs.shape[0], re_coefs.shape[1]

        def re_scores_of(coefs):
            # Flat gather through the sharded table: reshape to (S*E_s, d)
            # and index by shard*E_s + local. XLA lowers this to the one
            # all-gather of the (small) coefficient slabs per score merge.
            valid = re_shard_ids >= 0
            idx = jnp.maximum(re_shard_ids, 0) * E_s + jnp.maximum(re_local_ids, 0)
            w = coefs.reshape(S * E_s, -1)[idx]
            return jnp.where(valid, jnp.sum(re_features_flat * w, axis=-1), 0.0)

        fe_res = minimize_lbfgs_margin(
            fixed_objective,
            fe_batch.add_scores_to_offsets(re_scores_of(re_coefs)),
            w_fixed,
            fe_config,
        )
        w_fixed_new = fe_res.w

        fe_scores = fe_batch.margins(w_fixed_new)
        # (S, E_b, n_max) residual offsets: gather rows-sharded margins into
        # shard-sharded blocks (the second cross-device exchange).
        safe = jnp.maximum(re_block.sample_index, 0)
        offs = jnp.where(re_block.sample_index >= 0, fe_scores[safe], 0.0)

        def solve_one(feat, lab, wt, off, w_init):
            lb = LabeledBatch(lab, feat, off, wt)
            if re_solver == "newton":
                res = minimize_newton(re_objective, lb, w_init, re_config)
            else:
                res = minimize_lbfgs_margin(re_objective, lb, w_init, re_config)
            return res.w, res.evals

        def shard_solve(coefs_s, block_idx, feat, lab, wt, off_s, mask):
            # One shard's solves — device-local under the 'data' partition.
            w_init = coefs_s[jnp.maximum(block_idx, 0)]
            w_new, evals = jax.vmap(solve_one)(feat, lab, wt, off_s, w_init)
            w_new = jnp.where(mask[:, None], w_new, w_init)
            # Same drop-mode scatter discipline as the single-device program:
            # -1 padding rows route to the out-of-range filler slot E_s.
            slot = jnp.where(block_idx >= 0, block_idx, E_s)
            coefs_out = coefs_s.at[slot].set(w_new, mode="drop")
            visits = jnp.sum(evals * jnp.sum((wt > 0).astype(jnp.int32), axis=1))
            return coefs_out, visits

        re_coefs_new, shard_visits = jax.vmap(shard_solve)(
            re_coefs,
            re_block.entity_idx,
            re_block.features,
            re_block.label,
            re_block.weight,
            offs,
            re_block.train_mask,
        )

        total_scores = fe_scores + re_scores_of(re_coefs_new)
        return (
            w_fixed_new,
            re_coefs_new,
            total_scores,
            fe_res.evals,
            jnp.sum(shard_visits),
        )

    dp = dp_axes(mesh)
    repl = NamedSharding(mesh, P())
    rows = NamedSharding(mesh, P(dp))
    rows2d = NamedSharding(mesh, P(dp, None))
    shards1 = NamedSharding(mesh, P(dp))
    shards2 = NamedSharding(mesh, P(dp, None))
    shards3 = NamedSharding(mesh, P(dp, None, None))
    shards4 = NamedSharding(mesh, P(dp, None, None, None))

    def place(
        w_fixed, re_coefs, fe_batch, re_block, re_features_flat,
        re_shard_ids, re_local_ids,
    ):
        put = jax.device_put
        feats = fe_batch.features
        if isinstance(feats, SparseFeatures):
            feats = SparseFeatures(
                put(feats.indices, rows2d), put(feats.values, rows2d), feats.dim
            )
        else:
            feats = put(feats, rows2d)
        fe = LabeledBatch(
            label=put(fe_batch.label, rows),
            features=feats,
            offset=put(fe_batch.offset, rows),
            weight=put(fe_batch.weight, rows),
            uid=None,
        )
        rb = EntityBlock(
            entity_idx=put(re_block.entity_idx, shards2),
            features=put(re_block.features, shards4),
            label=put(re_block.label, shards3),
            weight=put(re_block.weight, shards3),
            sample_index=put(re_block.sample_index, shards3),
            train_mask=put(re_block.train_mask, shards2),
        )
        return (
            put(w_fixed, repl),
            put(re_coefs, shards3),
            fe,
            rb,
            put(re_features_flat, rows2d),
            put(re_shard_ids, rows),
            put(re_local_ids, rows),
        )

    return (
        jax.jit(step, out_shardings=(repl, shards1, rows, repl, repl)),
        place,
    )
