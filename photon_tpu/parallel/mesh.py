"""Device mesh construction and axis conventions.

Role parity: the reference's "cluster topology" is implicit in Spark
(executors + treeAggregate depth, SURVEY.md §2.8). Here topology is explicit:
a ``jax.sharding.Mesh`` whose axes name the framework's parallelism styles
(SURVEY.md §2.7 mapping):

- ``data``    — sample sharding; gradient reductions ride ICI psums
                (replaces broadcast + treeAggregate).
- ``entity``  — random-effect entity sharding (replaces the bin-packing
                RDD partitioner, RandomEffectDatasetPartitioner.scala:44-96).
- ``feature`` — feature-dimension sharding of w/gradient for coordinates too
                large for one chip's HBM (the TP analogue; reference handles
                this with sparse vectors + off-heap index maps).

A mesh is usually 1-D ``(data,)`` or 2-D ``(data, feature)``; the entity axis
aliases the data axis for GLMix jobs (fixed-effect batches and random-effect
entity blocks are both sharded over the same physical devices).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ENTITY_AXIS = "data"  # entities shard over the same physical axis as samples
FEATURE_AXIS = "feature"
SLICE_AXIS = "slice"  # multi-slice (DCN) outer data axis


def make_mesh(
    n_data: Optional[int] = None,
    n_feature: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, feature) mesh over the available devices.

    With ``n_feature == 1`` the mesh is effectively 1-D data-parallel; feature
    sharding multiplies in for very wide coordinates.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_feature
    assert n_data * n_feature <= len(devs), (
        f"mesh {n_data}x{n_feature} needs more than {len(devs)} devices"
    )
    grid = np.asarray(devs[: n_data * n_feature]).reshape(n_data, n_feature)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def make_multislice_mesh(
    n_slices: Optional[int] = None,
    n_feature: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(slice, data, feature) mesh for multi-slice pods.

    The outer ``slice`` axis maps to DCN, the inner ``data`` axis to ICI —
    gradient psums become hierarchical reductions (reduce inside each slice
    over ICI, then once across slices over DCN), the TPU equivalent of the
    reference's ``treeAggregate(depth=2)`` (SURVEY.md §2.8). Slice membership
    comes from ``device.slice_index`` when the runtime exposes it; pass
    ``n_slices`` to split explicitly (e.g. CPU tests).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_slices is None:
        idx = {getattr(d, "slice_index", 0) for d in devs}
        n_slices = max(len(idx), 1)
    assert len(devs) % n_slices == 0, (n_slices, len(devs))
    per_slice = len(devs) // n_slices
    assert per_slice % n_feature == 0, (per_slice, n_feature)
    # Slice-major ordering so each mesh row is one physical slice.
    devs = sorted(devs, key=lambda d: (getattr(d, "slice_index", 0), d.id))
    # Every slice_index group must hold exactly per_slice devices — an
    # uneven split would silently mix devices from different slices into
    # one mesh row, putting DCN traffic on the (supposedly ICI) data axis.
    slice_ids = [getattr(d, "slice_index", 0) for d in devs]
    if len(set(slice_ids)) > 1:
        from collections import Counter

        counts = Counter(slice_ids)
        assert len(counts) == n_slices and all(
            c == per_slice for c in counts.values()
        ), (
            f"uneven slice membership {dict(counts)}: need {n_slices} slices "
            f"of exactly {per_slice} devices each for a DCN-outer mesh"
        )
    grid = np.asarray(devs).reshape(n_slices, per_slice // n_feature, n_feature)
    return Mesh(grid, (SLICE_AXIS, DATA_AXIS, FEATURE_AXIS))


def dp_axes(mesh: Mesh) -> tuple:
    """The data-parallel mesh axes: ('slice', 'data') on a multi-slice mesh,
    ('data',) otherwise. Use as a PartitionSpec entry or a psum axis set."""
    if SLICE_AXIS in mesh.axis_names:
        return (SLICE_AXIS, DATA_AXIS)
    return (DATA_AXIS,)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Per-sample arrays: sharded on the data-parallel axes."""
    return NamedSharding(mesh, P(dp_axes(mesh)))

def batch_sharding(mesh: Mesh) -> NamedSharding:
    """(n, k) per-sample matrices (features/indices): row-sharded."""
    return NamedSharding(mesh, P(dp_axes(mesh), None))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

def feature_sharding(mesh: Mesh) -> NamedSharding:
    """Coefficient vectors sharded on the feature axis (wide coordinates)."""
    return NamedSharding(mesh, P(FEATURE_AXIS))
