"""Device mesh construction and axis conventions.

Role parity: the reference's "cluster topology" is implicit in Spark
(executors + treeAggregate depth, SURVEY.md §2.8). Here topology is explicit:
a ``jax.sharding.Mesh`` whose axes name the framework's parallelism styles
(SURVEY.md §2.7 mapping):

- ``data``    — sample sharding; gradient reductions ride ICI psums
                (replaces broadcast + treeAggregate).
- ``entity``  — random-effect entity sharding (replaces the bin-packing
                RDD partitioner, RandomEffectDatasetPartitioner.scala:44-96).
- ``feature`` — feature-dimension sharding of w/gradient for coordinates too
                large for one chip's HBM (the TP analogue; reference handles
                this with sparse vectors + off-heap index maps).

A mesh is usually 1-D ``(data,)`` or 2-D ``(data, feature)``; the entity axis
aliases the data axis for GLMix jobs (fixed-effect batches and random-effect
entity blocks are both sharded over the same physical devices).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ENTITY_AXIS = "data"  # entities shard over the same physical axis as samples
FEATURE_AXIS = "feature"


def make_mesh(
    n_data: Optional[int] = None,
    n_feature: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, feature) mesh over the available devices.

    With ``n_feature == 1`` the mesh is effectively 1-D data-parallel; feature
    sharding multiplies in for very wide coordinates.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devs) // n_feature
    assert n_data * n_feature <= len(devs), (
        f"mesh {n_data}x{n_feature} needs more than {len(devs)} devices"
    )
    grid = np.asarray(devs[: n_data * n_feature]).reshape(n_data, n_feature)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Per-sample arrays: sharded on the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))

def batch_sharding(mesh: Mesh) -> NamedSharding:
    """(n, k) per-sample matrices (features/indices): row-sharded."""
    return NamedSharding(mesh, P(DATA_AXIS, None))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

def feature_sharding(mesh: Mesh) -> NamedSharding:
    """Coefficient vectors sharded on the feature axis (wide coordinates)."""
    return NamedSharding(mesh, P(FEATURE_AXIS))
