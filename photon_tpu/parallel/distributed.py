"""Sharded data placement — the framework's "communication backend".

Role parity: reference §2.8 — Spark treeAggregate/broadcast/shuffle. Here the
entire backend is: place batches on the mesh with NamedShardings and jit the
objective/optimizer over them; XLA inserts the psum/all-gather collectives.
There is no aggregator code to maintain — ``GLMObjective``'s sums become
cross-device reductions purely by virtue of input sharding (the compiled
program is the SPMD equivalent of broadcast(w) + treeAggregate(add, merge),
reference ValueAndGradientAggregator.scala:300-321).

``shard_batch`` pads the batch to a device-divisible size with weight-0 rows
(weighted sums make padding exact, see LabeledBatch), so ragged inputs never
produce dynamic shapes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.parallel.mesh import dp_axes


def _pad_rows(a: jax.Array, target: int, fill=0):
    n = a.shape[0]
    if n == target:
        return a
    pad_width = [(0, target - n)] + [(0, 0)] * (a.ndim - 1)
    return jnp.pad(a, pad_width, constant_values=fill)


def pad_batch(batch: LabeledBatch, target_n: int) -> LabeledBatch:
    """Pad to ``target_n`` rows with weight-0 padding samples."""
    if batch.n == target_n:
        return batch
    assert target_n > batch.n
    feats = batch.features
    if isinstance(feats, SparseFeatures):
        feats = SparseFeatures(
            _pad_rows(feats.indices, target_n), _pad_rows(feats.values, target_n), feats.dim
        )
    else:
        feats = _pad_rows(feats, target_n)
    return LabeledBatch(
        label=_pad_rows(batch.label, target_n),
        features=feats,
        offset=_pad_rows(batch.offset, target_n),
        weight=_pad_rows(batch.weight, target_n),  # 0-weight padding
        uid=None if batch.uid is None else _pad_rows(batch.uid, target_n, fill=-1),
    )


def shard_batch(batch: LabeledBatch, mesh: Mesh) -> LabeledBatch:
    """Pad to a data-axis-divisible size and place on the mesh, samples
    sharded over the data-parallel axes, feature dim replicated."""
    dp = dp_axes(mesh)
    n_shards = int(np.prod([mesh.shape[a] for a in dp]))
    target = int(np.ceil(batch.n / n_shards) * n_shards)
    batch = pad_batch(batch, target)

    vec = NamedSharding(mesh, P(dp))
    mat = NamedSharding(mesh, P(dp, None))

    def place(x, sh):
        return jax.device_put(x, sh)

    feats = batch.features
    if isinstance(feats, SparseFeatures):
        feats = SparseFeatures(
            place(feats.indices, mat), place(feats.values, mat), feats.dim
        )
    else:
        feats = place(feats, mat)
    return LabeledBatch(
        label=place(batch.label, vec),
        features=feats,
        offset=place(batch.offset, vec),
        weight=place(batch.weight, vec),
        uid=None if batch.uid is None else place(batch.uid, vec),
    )


def replicate(x, mesh: Mesh):
    """Replicate a pytree across the mesh (broadcast role — one-time
    placement, not per-iteration: inside the jitted optimizer loop the
    replicated w never leaves the devices)."""
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), x)
