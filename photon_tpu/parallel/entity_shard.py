"""Entity→device-shard assignment shared by training and serving.

The multi-device GAME program shards the random-effect coefficient store by
ENTITY: each entity's block solves run on exactly one device, and the
serving hot store keeps that entity's rows on the same shard. Both sides
must agree on the assignment or a trained entity would be looked up on the
wrong serving shard — so the assignment is derived from ONE source of
truth: the consistent-hash ring already proven for fleet replica ownership
(serve/routing.py, the PR-13 disjoint-ownership scheme). Ring members are
the synthetic shard names ``"shard:0" … "shard:S-1"`` and the hashed key is
the SAME string the fleet router and ``serve/store._owned_mask`` hash — the
raw entity id when an EntityIndex exists, else the decimal dense index.

Device-count independence: the plan is built for a FIXED shard count
(default 8, the virtual-mesh width) regardless of how many devices are
present; shard ``s`` then maps onto device ``(s*n_devices)//S``
(contiguous blocks, matching sharded-table row chunking). Every device
count therefore sees the identical per-shard datasets and block geometry —
only placement changes — which is what makes multi-device training
bit-identical to the single-device run (same programs, same reduction
orders, different devices). Scaling the mesh never re-buckets a block.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.serve.routing import HashRing

DEFAULT_N_SHARDS = 8


def shard_members(n_shards: int) -> Tuple[str, ...]:
    """Canonical ring member names for device shards."""
    return tuple(f"shard:{k}" for k in range(int(n_shards)))


def shard_of_member(member: str) -> int:
    return int(member.split(":", 1)[1])


@dataclasses.dataclass(frozen=True)
class EntityShardPlan:
    """Frozen entity→shard assignment for one RE type.

    shard_of:  (E,) int32 — owning shard of each dense entity index.
    local_of:  (E,) int32 — entity's row in its shard's LOCAL index space
               (entities of a shard are numbered in ascending global order).
    counts:    (S,) int64 — entities per shard.
    """

    n_shards: int
    seed: int
    ring_version: int
    shard_of: np.ndarray
    local_of: np.ndarray
    counts: np.ndarray

    @property
    def num_entities(self) -> int:
        return int(self.shard_of.shape[0])

    def entities_of(self, shard: int) -> np.ndarray:
        """Global entity indices owned by ``shard``, ascending (the local
        index space: position j here is local entity j)."""
        return np.flatnonzero(self.shard_of == shard)

    def device_of(self, shard: int, n_devices: int) -> int:
        """Shard → device under an n-device mesh: contiguous blocks of
        S/n shards per device. Matches how a shard-grouped hot table
        sharded ``NamedSharding(mesh, P('data'))`` chunks its rows over
        the mesh, so a trained shard and its serving rows land on the
        SAME device. Every device count reuses the same fixed-S plan —
        only this mapping changes."""
        return (int(shard) * int(n_devices)) // self.n_shards

    def shard_sample_entities(self, entity_ids: np.ndarray) -> List[np.ndarray]:
        """Per-shard localized sample entity ids: for shard s, a (n,) int32
        array holding each sample's LOCAL entity index when the sample's
        entity belongs to s, else -1 (the dataset builder drops negative
        ids, so building per-shard datasets from these is a pure filter —
        sample_index keeps pointing at the GLOBAL flat batch rows)."""
        entity_ids = np.asarray(entity_ids)
        valid = entity_ids >= 0
        safe = np.where(valid, entity_ids, 0)
        owner = self.shard_of[safe]
        local = self.local_of[safe]
        return [
            np.where(valid & (owner == s), local, -1).astype(np.int32)
            for s in range(self.n_shards)
        ]

    def snapshot(self) -> dict:
        """Comparable identity of the assignment (tests assert the serving
        store derives the same one)."""
        return dict(
            n_shards=self.n_shards,
            seed=self.seed,
            ring_version=self.ring_version,
            shard_of=self.shard_of.tolist(),
        )


def build_shard_plan(
    num_entities: int,
    n_shards: int = DEFAULT_N_SHARDS,
    seed: int = 0,
    entity_index=None,
    vnodes: int = 64,
    ring: Optional[HashRing] = None,
) -> EntityShardPlan:
    """Assign dense entity indices to device shards via the consistent-hash
    ring. Hashes the SAME per-entity string ``serve/store._owned_mask``
    hashes (raw entity id through ``entity_index`` when present, else the
    decimal index), so training and serving agree by construction."""
    if ring is None:
        ring = HashRing(shard_members(n_shards), vnodes=vnodes, seed=seed)
    shard_of = np.empty((num_entities,), np.int32)
    for i in range(num_entities):
        key = entity_index.entity_id(i) if entity_index is not None else i
        shard_of[i] = shard_of_member(ring.owner(str(key)))
    local_of = np.full((num_entities,), -1, np.int32)
    counts = np.zeros((n_shards,), np.int64)
    for s in range(n_shards):
        ents = np.flatnonzero(shard_of == s)
        local_of[ents] = np.arange(ents.size, dtype=np.int32)
        counts[s] = ents.size
    return EntityShardPlan(
        n_shards=int(n_shards),
        seed=int(seed),
        ring_version=int(ring.version),
        shard_of=shard_of,
        local_of=local_of,
        counts=counts,
    )


def merge_shard_coefficients(
    plan: EntityShardPlan,
    shard_coefs: Sequence[np.ndarray],
    dim: int,
    dtype=np.float32,
) -> np.ndarray:
    """Scatter per-shard (E_s, d) coefficient tables into one global (E, d)
    host table — the coordinate path's score/residual merge. Shards own
    DISJOINT entity sets, so the merge is exact (no summation, no order
    dependence)."""
    out = np.zeros((plan.num_entities, dim), dtype)
    for s, w in enumerate(shard_coefs):
        ents = plan.entities_of(s)
        if ents.size:
            out[ents] = np.asarray(w)[: ents.size, :dim]
    return out
