"""Feature-dimension-sharded fixed-effect training (the TP analogue).

Parity target: the reference's answer to coefficient vectors too large for
one machine — sparse Breeze vectors plus the off-heap PalDB feature index
(photon-api index/PalDBIndexMap.scala:43-240) so "hundreds of billions of
coefficients" (README.md:56) never materialize on the driver. The TPU
analogue (SURVEY.md §2.7/§5): shard ``w`` and its gradient over the mesh's
``feature`` axis so a single fixed-effect coordinate can exceed one chip's
HBM.

Design (shard_map over a (data, feature) mesh):

- Each device along ``feature`` owns a contiguous coefficient range
  ``[lo, lo + d/F)`` of the global dimension; ``w`` lives sharded
  ``P('feature')`` and is never gathered.
- Sparse batches keep GLOBAL feature indices, rows sharded ``P('data')`` and
  replicated along ``feature``. Each device resolves only the indices that
  land in its range (mask + local gather); partial margins are psummed over
  ``feature`` — a (n_local,) all-reduce on ICI instead of an all-gather of a
  10B-coefficient vector.
- The gradient is scatter-added into the LOCAL coefficient range (each device
  owns its features outright) and psummed over ``data`` only — the same
  reduction Spark's treeAggregate performs, minus the driver round-trip.

L-BFGS runs unchanged on top: its two-loop recursion is built from dots and
axpys over (m, d) history arrays which XLA partitions along ``feature``
automatically once ``w`` is sharded (history inherits the sharding; the dots
become psums on ICI).

Normalization: scale ``factors`` fold in (a local gather, like values);
``shifts`` densify sparse rows (reference hits the same wall —
HessianMatrixAggregator.scala:27-28) and are rejected.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map graduated from jax.experimental to the jax namespace; accept
# whichever this build ships.
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:  # pragma: no cover - depends on jax version
    from jax.experimental.shard_map import shard_map as _shard_map

from photon_tpu.data.batch import LabeledBatch, SparseFeatures
from photon_tpu.ops.objective import GLMObjective
from photon_tpu.optim.common import OptimizeResult, OptimizerConfig
from photon_tpu.optim.lbfgs import minimize_lbfgs
from photon_tpu.parallel.mesh import FEATURE_AXIS, dp_axes

Array = jax.Array


def padded_dim(dim: int, n_feature_shards: int) -> int:
    """Global coefficient dim padded so every feature shard is equal-sized.
    Padded coefficients start at 0, receive zero data gradient and zero L2
    gradient, and therefore stay exactly 0 through any quasi-Newton run."""
    f = n_feature_shards
    return int(np.ceil(dim / f) * f)


def _check_objective(objective: GLMObjective) -> None:
    norm = objective.normalization
    if norm is not None and norm.shifts is not None:
        raise ValueError(
            "feature-sharded training supports scale normalization only: "
            "shift normalization densifies sparse rows (same limitation the "
            "reference documents in HessianMatrixAggregator.scala:27-28); "
            "standardize to scale-only or use the replicated path"
        )


def _local_window(indices, values, shard, factors_loc):
    """Per-device view of globally-indexed sparse rows: indices mapped into
    this shard's coefficient range, values factor-folded, validity mask
    applied. The ONE place the sharding-critical window math lives — the
    gradient and Hessian paths must stay byte-for-byte consistent."""
    lo = jax.lax.axis_index(FEATURE_AXIS) * shard
    local_idx = indices - lo
    valid = (local_idx >= 0) & (local_idx < shard)
    local_idx = jnp.clip(local_idx, 0, shard - 1)
    vals = values
    if factors_loc is not None:
        vals = vals * jnp.where(valid, factors_loc[local_idx], 0.0)
    return local_idx, valid, vals


def _acc_dtype(w_dtype):
    """Accumulation dtype: at LEAST float32 (bf16 values would degrade the
    margins, the gradient, and through them the curvature pairs — same
    preferred_element_type discipline as ops/pallas_glm), but float64 is
    preserved when the coefficients are f64 (the dryrun's tight
    x64-on-CPU parity certification runs the same program at f64)."""
    return w_dtype if w_dtype == jnp.float64 else jnp.float32


def _l2_masked_local(x_loc, shard, intercept):
    """Local shard of x with the (globally-indexed) intercept zeroed."""
    xm = x_loc.astype(_acc_dtype(x_loc.dtype))
    if intercept is not None:
        lo = jax.lax.axis_index(FEATURE_AXIS) * shard
        pos = jnp.arange(shard) + lo
        xm = jnp.where(pos == intercept, 0.0, xm)
    return xm


def sparse_value_and_grad_feature_sharded(
    objective: GLMObjective, mesh: Mesh, dim: int
):
    """Build ``(w, batch) -> (value, grad)`` for a sparse LabeledBatch with
    ``w`` sharded over FEATURE_AXIS and rows sharded over DATA_AXIS.

    ``dim`` is the PADDED global dimension (a multiple of the feature-axis
    size). The returned function is jittable; ``batch.features`` must be
    SparseFeatures carrying global indices.
    """
    _check_objective(objective)
    n_feat = mesh.shape[FEATURE_AXIS]
    dp = dp_axes(mesh)
    assert dim % n_feat == 0, f"dim {dim} not divisible by feature axis {n_feat}"
    shard = dim // n_feat
    loss = objective.loss
    l2 = objective.l2_weight
    intercept = objective.intercept_index
    factors = None if objective.normalization is None else objective.normalization.factors

    def local_fn(w_loc, indices, values, label, offset, weight, factors_loc):
        """Runs per device: w_loc (shard,), rows local along data."""
        local_idx, valid, vals = _local_window(indices, values, shard, factors_loc)

        # Accumulation in _acc_dtype (≥ f32; f64 preserved for the x64
        # parity certification) regardless of the feature-value dtype.
        acc = _acc_dtype(w_loc.dtype)
        gathered = jnp.where(valid, w_loc[local_idx], 0.0)
        z_partial = jnp.sum(
            (vals * gathered).astype(acc), axis=-1
        )
        z = jax.lax.psum(z_partial, FEATURE_AXIS) + offset

        lv = loss.value(z, label)
        dz = weight * loss.dz(z, label)
        loss_local = jnp.sum(weight * lv).astype(acc)

        # Scatter-add into the local coefficient range only.
        contrib = jnp.where(valid, vals * dz[:, None], 0.0).astype(acc)
        grad_loc = jnp.zeros((shard,), acc).at[
            local_idx.reshape(-1)
        ].add(contrib.reshape(-1))
        grad_loc = jax.lax.psum(grad_loc, dp)

        # L2 on the local shard; the (global) intercept is exempt.
        if l2 != 0.0:
            wm = _l2_masked_local(w_loc, shard, intercept)
            grad_loc = grad_loc + l2 * wm
            l2_local = 0.5 * l2 * jnp.sum(wm * wm)
        else:
            l2_local = jnp.zeros((), acc)

        value = jax.lax.pmean(
            jax.lax.psum(loss_local, dp), FEATURE_AXIS
        ) + jax.lax.pmean(jax.lax.psum(l2_local, FEATURE_AXIS), dp)
        return value, grad_loc

    in_specs = (
        P(FEATURE_AXIS),          # w
        P(dp, None),              # indices
        P(dp, None),              # values
        P(dp),                    # label
        P(dp),                    # offset
        P(dp),                    # weight
    )
    factor_spec = (P(FEATURE_AXIS),) if factors is not None else ()
    shmapped = _shard_map(
        (lambda w, i, v, y, o, wt, f: local_fn(w, i, v, y, o, wt, f))
        if factors is not None
        else (lambda w, i, v, y, o, wt: local_fn(w, i, v, y, o, wt, None)),
        mesh=mesh,
        in_specs=in_specs + factor_spec,
        out_specs=(P(), P(FEATURE_AXIS)),
    )

    def value_and_grad(w: Array, batch: LabeledBatch) -> Tuple[Array, Array]:
        feats = batch.features
        assert isinstance(feats, SparseFeatures)
        args = (w, feats.indices, feats.values, batch.label, batch.offset, batch.weight)
        if factors is not None:
            args = args + (factors,)
        return shmapped(*args)

    return value_and_grad


def sparse_linearized_hvp_feature_sharded(
    objective: GLMObjective, mesh: Mesh, dim: int
):
    """Build ``make_hvp(w, batch) -> (v -> H(w)·v)`` with ``w``/``v``
    feature-sharded and rows data-sharded — the distributed counterpart of
    GLMObjective.linearized_hvp (reference: the distributed objective's
    hessianVector treeAggregate, HessianVectorAggregator.scala, one round
    per CG product). Curvature d2 = weight·loss''(z,y) is computed ONCE per
    outer iterate (one sharded margins pass, psum over ``feature``); each
    product is then one forward + one scatter-add transpose pass with a
    psum over ``feature`` (for u) and one over ``data`` (for the result) —
    both on ICI.
    """
    _check_objective(objective)
    n_feat = mesh.shape[FEATURE_AXIS]
    dp = dp_axes(mesh)
    assert dim % n_feat == 0, f"dim {dim} not divisible by feature axis {n_feat}"
    shard = dim // n_feat
    loss = objective.loss
    l2 = objective.l2_weight
    intercept = objective.intercept_index
    factors = None if objective.normalization is None else objective.normalization.factors

    def local_d2(w_loc, indices, values, label, offset, weight, factors_loc):
        local_idx, valid, vals = _local_window(indices, values, shard, factors_loc)
        gathered = jnp.where(valid, w_loc[local_idx], 0.0)
        z_partial = jnp.sum(
            (vals * gathered).astype(_acc_dtype(w_loc.dtype)), axis=-1
        )
        z = jax.lax.psum(z_partial, FEATURE_AXIS) + offset
        return weight * loss.dzz(z, label)

    def local_hv(v_loc, indices, values, d2, factors_loc):
        local_idx, valid, vals = _local_window(indices, values, shard, factors_loc)
        acc = _acc_dtype(v_loc.dtype)
        v_gather = jnp.where(valid, v_loc[local_idx], 0.0)
        u_partial = jnp.sum((vals * v_gather).astype(acc), axis=-1)
        u = jax.lax.psum(u_partial, FEATURE_AXIS)  # (A·v) on each data shard
        t = d2 * u
        contrib = jnp.where(valid, vals * t[:, None], 0.0).astype(acc)
        hv_loc = jnp.zeros((shard,), acc).at[
            local_idx.reshape(-1)
        ].add(contrib.reshape(-1))
        hv_loc = jax.lax.psum(hv_loc, dp)
        if l2 != 0.0:
            hv_loc = hv_loc + l2 * _l2_masked_local(v_loc, shard, intercept)
        return hv_loc

    row_specs = (P(dp, None), P(dp, None))  # indices, values
    factor_spec = (P(FEATURE_AXIS),) if factors is not None else ()
    d2_shmapped = _shard_map(
        (lambda w, i, v, y, o, wt, f: local_d2(w, i, v, y, o, wt, f))
        if factors is not None
        else (lambda w, i, v, y, o, wt: local_d2(w, i, v, y, o, wt, None)),
        mesh=mesh,
        in_specs=(P(FEATURE_AXIS),) + row_specs + (P(dp), P(dp), P(dp)) + factor_spec,
        out_specs=P(dp),
    )
    hv_shmapped = _shard_map(
        (lambda v, i, vl, d2, f: local_hv(v, i, vl, d2, f))
        if factors is not None
        else (lambda v, i, vl, d2: local_hv(v, i, vl, d2, None)),
        mesh=mesh,
        in_specs=(P(FEATURE_AXIS),) + row_specs + (P(dp),) + factor_spec,
        out_specs=P(FEATURE_AXIS),
    )

    def make_hvp(w: Array, batch: LabeledBatch):
        feats = batch.features
        assert isinstance(feats, SparseFeatures)
        args = (w, feats.indices, feats.values, batch.label, batch.offset, batch.weight)
        if factors is not None:
            args = args + (factors,)
        d2 = d2_shmapped(*args)

        def hv(v: Array) -> Array:
            hv_args = (v, feats.indices, feats.values, d2)
            if factors is not None:
                hv_args = hv_args + (factors,)
            return hv_shmapped(*hv_args)

        return hv

    return make_hvp


def place_feature_sharded(
    mesh: Mesh, w: Array, batch: LabeledBatch
) -> Tuple[Array, LabeledBatch]:
    """device_put ``w`` P('feature') and the sparse batch rows P('data')."""
    dp = dp_axes(mesh)
    wsh = NamedSharding(mesh, P(FEATURE_AXIS))
    rows = NamedSharding(mesh, P(dp))
    rows2d = NamedSharding(mesh, P(dp, None))
    feats = batch.features
    assert isinstance(feats, SparseFeatures)
    put = jax.device_put
    feats = SparseFeatures(put(feats.indices, rows2d), put(feats.values, rows2d), feats.dim)
    placed = LabeledBatch(
        label=put(batch.label, rows),
        features=feats,
        offset=put(batch.offset, rows),
        weight=put(batch.weight, rows),
        uid=None if batch.uid is None else put(batch.uid, rows),
    )
    return put(w, wsh), placed


def train_fixed_effect_feature_sharded(
    mesh: Mesh,
    objective: GLMObjective,
    config: OptimizerConfig,
    dim: int,
    box: Optional[Tuple[Array, Array]] = None,
    solver: str = "lbfgs",
    max_cg_iter: int = 20,
):
    """Jitted fit of a sparse fixed-effect coordinate with ``w``
    feature-sharded over the mesh (reference FixedEffectCoordinate.trainModel
    role, FixedEffectCoordinate.scala:115-129, for coordinates whose ``w``
    exceeds one chip's HBM).

    ``solver``: ``"lbfgs"`` (default) or ``"tron"`` — TRON rides the
    sharded linearized HVP (one psum pair per CG product, the reference's
    distributed hessianVector).

    Returns ``fit(w0, batch) -> OptimizeResult`` with ``result.w`` sharded
    P('feature'). ``dim`` must be pre-padded (see ``padded_dim``).
    """
    if solver not in ("lbfgs", "tron"):
        raise ValueError(f"unknown feature-sharded solver {solver!r}")
    vg = sparse_value_and_grad_feature_sharded(objective, mesh, dim)
    make_hvp = (
        sparse_linearized_hvp_feature_sharded(objective, mesh, dim)
        if solver == "tron"
        else None
    )

    @functools.partial(
        jax.jit,
        in_shardings=(
            NamedSharding(mesh, P(FEATURE_AXIS)),
            NamedSharding(mesh, P(dp_axes(mesh))),
            NamedSharding(mesh, P(dp_axes(mesh), None)),
            NamedSharding(mesh, P(dp_axes(mesh), None)),
            NamedSharding(mesh, P(dp_axes(mesh))),
            NamedSharding(mesh, P(dp_axes(mesh))),
        ),
    )
    def fit(w0, label, indices, values, offset, weight) -> OptimizeResult:
        batch = LabeledBatch(
            label, SparseFeatures(indices, values, dim), offset, weight
        )
        if solver == "tron":
            from photon_tpu.optim.tron import minimize_tron

            return minimize_tron(
                lambda w: vg(w, batch), None, w0, config, max_cg_iter, box,
                hvp_factory=lambda w: make_hvp(w, batch),
            )
        return minimize_lbfgs(lambda w: vg(w, batch), w0, config, box=box)

    def fit_batch(w0: Array, batch: LabeledBatch) -> OptimizeResult:
        feats = batch.features
        assert isinstance(feats, SparseFeatures)
        return fit(
            w0, batch.label, feats.indices, feats.values, batch.offset, batch.weight
        )

    return fit_batch
