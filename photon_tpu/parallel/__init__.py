from photon_tpu.parallel.mesh import make_mesh, DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS  # noqa: F401
from photon_tpu.parallel.distributed import shard_batch, replicate  # noqa: F401
from photon_tpu.parallel.feature_sharded import (  # noqa: F401
    padded_dim,
    place_feature_sharded,
    sparse_value_and_grad_feature_sharded,
    train_fixed_effect_feature_sharded,
)
from photon_tpu.parallel.entity_shard import (  # noqa: F401
    DEFAULT_N_SHARDS,
    EntityShardPlan,
    build_shard_plan,
    merge_shard_coefficients,
    shard_members,
)
