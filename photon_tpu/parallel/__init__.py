from photon_tpu.parallel.mesh import make_mesh, DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS  # noqa: F401
from photon_tpu.parallel.distributed import shard_batch, replicate  # noqa: F401
