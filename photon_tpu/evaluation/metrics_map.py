"""Per-model validation MetricsMap — the legacy driver's full metric set.

Mirrors the reference's ``Evaluation.evaluate``
(photon-client .../evaluation/Evaluation.scala:31-128): every validated
model gets a map of metric name → value whose FACETS are selected by task —

- regression tasks (linear, Poisson): mean absolute error, mean square
  error, root mean square error over mean-function predictions
  (Evaluation.scala:66-74);
- binary classifiers (logistic, smoothed hinge): area under PR, area
  under ROC, peak F1 score over score thresholds (Evaluation.scala:79-90);
- likelihood models: per-datum log-likelihood — logistic from the mean
  scores with EPSILON clamping (Evaluation.scala:147-160), Poisson from
  the margins (Evaluation.scala:131-144);
- AIC with the small-sample correction whenever a log-likelihood exists,
  with effective parameters = #{|coeff| > 1e-9} (Evaluation.scala:104-123).

Metric NAMES are the reference's exact strings so logs and serialized
metric maps line up across frameworks. Like the reference, the map is
UNWEIGHTED — ``Evaluation.evaluate`` ignores LabeledPoint weights (its
RegressionMetrics/BinaryClassificationMetrics run on bare (score, label)
pairs and ``averageLogLikelihoodRDD`` counts 1 per datum); weighted
evaluation lives in ``evaluation.suite.EvaluationSuite``. Model selection
per task follows ModelSelection.scala:36-63 (logistic → AUROC↑,
linear → RMSE↓, Poisson → per-datum log-likelihood↑; smoothed hinge joins
the BinaryClassifier rule, AUROC↑).

The per-metric reductions run on device; only final scalars come back.
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
from jax.scipy.special import gammaln

from photon_tpu.evaluation.evaluators import Array, auc_pr, auc_roc, peak_f1
from photon_tpu.types import TaskType

MEAN_ABSOLUTE_ERROR = "Mean absolute error"
MEAN_SQUARE_ERROR = "Mean square error"
ROOT_MEAN_SQUARE_ERROR = "Root mean square error"
AREA_UNDER_PRECISION_RECALL = "Area under precision/recall"
AREA_UNDER_ROC = "Area under ROC"
PEAK_F1_SCORE = "Peak F1 score"
DATA_LOG_LIKELIHOOD = "Per-datum log likelihood"
AKAIKE_INFORMATION_CRITERION = "Akaike information criterion"
EPSILON = 1e-9

_REGRESSION_TASKS = (TaskType.LINEAR_REGRESSION, TaskType.POISSON_REGRESSION)
_BINARY_TASKS = (
    TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM
)


def mean_function(task: TaskType, margins: Array) -> Array:
    """computeMeanFunctionWithOffset: margins (x·w + offset) → predictions
    on the label scale (GeneralizedLinearModel.scala mean-function role)."""
    if task == TaskType.LOGISTIC_REGRESSION:
        return 1.0 / (1.0 + jnp.exp(-margins))
    if task == TaskType.POISSON_REGRESSION:
        return jnp.exp(margins)
    # Linear regression and smoothed-hinge SVM score on the margin itself.
    return margins


def _log_likelihood_per_datum(
    task: TaskType, margins: Array, predictions: Array, labels: Array
) -> Optional[Array]:
    if task == TaskType.LOGISTIC_REGRESSION:
        p = jnp.asarray(predictions, jnp.float32)
        log_p = jnp.log(jnp.maximum(p, EPSILON))
        log_1mp = jnp.where(
            p > 1.0 - EPSILON, math.log(EPSILON), jnp.log1p(-p)
        )
        ll = labels * log_p + (1.0 - labels) * log_1mp
    elif task == TaskType.POISSON_REGRESSION:
        z = jnp.asarray(margins, jnp.float32)
        # y·wTx − e^{wTx} − log Γ(1+y)  (Evaluation.scala:136-139)
        ll = labels * z - jnp.exp(z) - gammaln(1.0 + labels)
    else:
        return None
    return jnp.mean(ll)  # averageLogLikelihoodRDD: 1 count per datum


def metrics_map(
    task: TaskType,
    margins: Array,
    labels: Array,
    coefficients: Optional[Array] = None,
) -> Dict[str, float]:
    """The reference's per-model MetricsMap, computed from the validation
    margins (Evaluation.evaluate, Evaluation.scala:31-128)."""
    labels = jnp.asarray(labels, jnp.float32)
    preds = mean_function(task, jnp.asarray(margins, jnp.float32))
    out: Dict[str, float] = {}

    if task in _REGRESSION_TASKS:
        err = preds - labels
        mse = jnp.mean(err * err)
        out[MEAN_ABSOLUTE_ERROR] = float(jnp.mean(jnp.abs(err)))
        out[MEAN_SQUARE_ERROR] = float(mse)
        out[ROOT_MEAN_SQUARE_ERROR] = float(jnp.sqrt(mse))

    if task in _BINARY_TASKS:
        # Rank metrics see the MARGINS, not the sigmoid means: the mean
        # function is monotone so AUROC/AUPR/peak-F1 are identical in exact
        # arithmetic, but f32 sigmoid saturates to exactly 0/1 beyond
        # |margin| ≈ 17, creating artificial ties that can flip model
        # selection between near-identical sweeps.
        scores = jnp.asarray(margins, jnp.float32)
        out[AREA_UNDER_PRECISION_RECALL] = float(auc_pr(scores, labels))
        out[AREA_UNDER_ROC] = float(auc_roc(scores, labels))
        out[PEAK_F1_SCORE] = float(peak_f1(scores, labels))

    ll = _log_likelihood_per_datum(task, margins, preds, labels)
    if ll is not None:
        ll = float(ll)
        out[DATA_LOG_LIKELIHOOD] = ll
        if coefficients is not None:
            n = labels.shape[0]  # scoreAndLabel.count(): samples, unweighted
            k = int(jnp.sum(jnp.abs(jnp.asarray(coefficients)) > 1e-9))
            base_aic = 2.0 * (k - n * ll)
            den = n - k - 1.0
            # Scala doubles yield ±Infinity at den == 0 and the reference
            # logs it harmlessly; Python float division would raise instead.
            corr = math.inf if den == 0 else 2.0 * k * (k + 1) / den
            out[AKAIKE_INFORMATION_CRITERION] = base_aic + corr
    return out


def sanitize_for_json(obj):
    """Recursively replace non-finite floats with None for serialization.

    The in-memory MetricsMap keeps Scala-double parity (AIC can be
    ``math.inf`` at the n−k−1 = 0 pole), but ``json.dump`` would emit the
    non-RFC token ``Infinity`` that strict parsers reject — training
    summaries sanitize through this helper at write time instead.
    """
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: sanitize_for_json(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize_for_json(v) for v in obj]
    return obj


# ModelSelection.scala:36-63 — (metric name, larger_is_better) per task.
_SELECTION: Dict[TaskType, Tuple[str, bool]] = {
    TaskType.LOGISTIC_REGRESSION: (AREA_UNDER_ROC, True),
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: (AREA_UNDER_ROC, True),
    TaskType.LINEAR_REGRESSION: (ROOT_MEAN_SQUARE_ERROR, False),
    TaskType.POISSON_REGRESSION: (DATA_LOG_LIKELIHOOD, True),
}


def selection_metric(task: TaskType) -> Tuple[str, bool]:
    return _SELECTION[task]
