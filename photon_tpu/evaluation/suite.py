"""EvaluationSuite: a set of evaluators sharing one validation batch.

Parity target: reference ``EvaluationSuite`` (photon-lib
evaluation/EvaluationSuite.scala:34-95 — shared label/offset/weight RDD joined
against scores; here the batch IS aligned, no join) and
``MultiEvaluatorType`` spec strings like ``AUC:queryId`` / ``PRECISION@5:docId``
(evaluation/MultiEvaluatorType.scala:52-72 regex grammar).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Tuple

import jax

from photon_tpu.data.game_data import GameBatch
from photon_tpu.evaluation.evaluators import (
    EvaluatorType,
    evaluate,
    grouped_auc,
    grouped_precision_at_k,
    metric_is_better,
)
from photon_tpu.models.game import GameModel

Array = jax.Array

_MULTI_RE = re.compile(r"^(AUC|PRECISION@(\d+)):(\w+)$")


@dataclasses.dataclass(frozen=True)
class EvaluatorSpec:
    """One evaluator: plain (AUC, RMSE, ...) or grouped (AUC:entityType)."""

    name: str
    etype: EvaluatorType
    group_by: Optional[str] = None  # entity-id column for multi evaluators
    k: int = 10

    @staticmethod
    def parse(spec: str) -> "EvaluatorSpec":
        """Parse reference-grammar evaluator strings: plain enum names, or
        ``AUC:idColumn`` / ``PRECISION@k:idColumn`` for multi evaluators."""
        m = _MULTI_RE.match(spec.strip())
        if m:
            if m.group(1) == "AUC":
                return EvaluatorSpec(spec, EvaluatorType.AUC, group_by=m.group(3))
            return EvaluatorSpec(
                spec, EvaluatorType.PRECISION_AT_K, group_by=m.group(3), k=int(m.group(2))
            )
        name = spec.strip().upper()
        if name.startswith("PRECISION@"):
            return EvaluatorSpec(spec, EvaluatorType.PRECISION_AT_K, k=int(name.split("@")[1]))
        return EvaluatorSpec(spec, EvaluatorType[name])

    def better(self) -> Callable[[float, float], bool]:
        return metric_is_better(self.etype)


class EvaluationSuite:
    """Evaluates a GameModel (or raw scores) on a validation batch."""

    def __init__(self, specs: List[EvaluatorSpec], num_entities: Optional[Dict[str, int]] = None):
        if not specs:
            raise ValueError("EvaluationSuite needs at least one evaluator")
        self.specs = specs
        self.num_entities = num_entities or {}

    @property
    def primary(self) -> EvaluatorSpec:
        return self.specs[0]

    def evaluate_scores(self, scores: Array, batch: GameBatch) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for spec in self.specs:
            if spec.group_by is not None:
                gids = batch.entity_ids[spec.group_by]
                n_groups = self.num_entities.get(spec.group_by)
                if n_groups is None:
                    n_groups = int(jax.numpy.max(gids)) + 1
                if spec.etype == EvaluatorType.AUC:
                    v = grouped_auc(scores, batch.label, gids, n_groups, batch.weight)
                else:
                    v = grouped_precision_at_k(scores, batch.label, gids, n_groups, spec.k)
            else:
                v = evaluate(spec.etype, scores, batch.label, batch.weight, spec.k)
            out[spec.name] = float(v)
        return out

    def evaluate_model(self, model: GameModel, batch: GameBatch) -> Dict[str, float]:
        scores = model.score_with_offset(batch)
        return self.evaluate_scores(scores, batch)

    def validation_fn(self) -> Callable[[GameModel, GameBatch], Dict[str, float]]:
        return self.evaluate_model
