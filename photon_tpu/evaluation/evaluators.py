"""Validation metrics: AUC / AUPR / RMSE / losses / Precision@k + grouped
(multi-) evaluators.

Parity targets: reference evaluator implementations in photon-api
evaluation/ (AreaUnderROCCurveEvaluator + Local/Multi variants with weighted
trapezoid AUC AreaUnderROCCurveLocalEvaluator.scala:26-72, RMSEEvaluator,
SquaredLossEvaluator, LogisticLossEvaluator, PoissonLossEvaluator,
PrecisionAtK{Local,Multi}Evaluator) and the lib-level Evaluator /
MultiEvaluator machinery (photon-lib evaluation/MultiEvaluator.scala:36-72,
EvaluatorType.scala:59-64 with direction-of-better op).

TPU-first design (SURVEY.md §7 hard part #2 — distributed AUC without a
driver-side sort): metrics are computed fully on device. AUC handles weighted
samples and score ties exactly via a sort + tie-group segment-sum formulation
(equivalent to the weighted trapezoid over the ROC curve). Grouped
("multi") evaluators take a dense group-id vector and use one shared sort +
segment reductions — the reference's groupBy(id)+local-metric pattern with
the shuffle replaced by segment ops.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from photon_tpu.ops.losses import LogisticLoss, PoissonLoss, SquaredLoss

Array = jax.Array


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    AUPR = "AUPR"
    RMSE = "RMSE"
    SQUARED_LOSS = "SQUARED_LOSS"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    PRECISION_AT_K = "PRECISION_AT_K"


# Direction-of-better per metric (reference EvaluatorType.op :59-64).
_LARGER_IS_BETTER = {
    EvaluatorType.AUC: True,
    EvaluatorType.AUPR: True,
    EvaluatorType.PRECISION_AT_K: True,
    EvaluatorType.RMSE: False,
    EvaluatorType.SQUARED_LOSS: False,
    EvaluatorType.LOGISTIC_LOSS: False,
    EvaluatorType.POISSON_LOSS: False,
}


def metric_is_better(etype: EvaluatorType) -> Callable[[float, float], bool]:
    if _LARGER_IS_BETTER[etype]:
        return lambda new, old: new > old
    return lambda new, old: new < old


def _default_weight(scores: Array, weight: Optional[Array]) -> Array:
    return jnp.ones_like(scores) if weight is None else weight


def _tie_groups(sorted_scores: Array) -> Array:
    """Dense group id per sorted element; equal scores share a group."""
    new_group = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (sorted_scores[1:] != sorted_scores[:-1]).astype(jnp.int32)]
    )
    return jnp.cumsum(new_group) - 1


def auc_roc(scores: Array, labels: Array, weight: Optional[Array] = None) -> Array:
    """Weighted ROC AUC with exact tie handling.

    AUC = Σ_pos w_p · (W_neg,below(p) + ½·W_neg,tied(p)) / (W_pos · W_neg) —
    the probability a random positive outranks a random negative (ties count
    ½), identical to the reference's weighted trapezoid
    (AreaUnderROCCurveLocalEvaluator.scala:26-72).
    """
    w = _default_weight(scores, weight)
    n = scores.shape[0]
    order = jnp.argsort(scores)  # ascending
    s, y, ww = scores[order], labels[order], w[order]
    pos_w = jnp.where(y > 0, ww, 0.0)
    neg_w = jnp.where(y > 0, 0.0, ww)

    gid = _tie_groups(s)
    group_neg = jax.ops.segment_sum(neg_w, gid, num_segments=n)
    # Exclusive cumulative negative weight below each tie group.
    cum_neg = jnp.cumsum(group_neg) - group_neg
    frac = cum_neg[gid] + 0.5 * group_neg[gid]
    Wp = jnp.sum(pos_w)
    Wn = jnp.sum(neg_w)
    return jnp.sum(pos_w * frac) / jnp.maximum(Wp * Wn, 1e-30)


def auc_pr(scores: Array, labels: Array, weight: Optional[Array] = None) -> Array:
    """Weighted area under the precision-recall curve (trapezoid between
    distinct-score cut points, reference AreaUnderPRCurveEvaluator role)."""
    w = _default_weight(scores, weight)
    n = scores.shape[0]
    order = jnp.argsort(-scores)  # descending: threshold sweep
    s, y, ww = scores[order], labels[order], w[order]
    pos_w = jnp.where(y > 0, ww, 0.0)
    neg_w = jnp.where(y > 0, 0.0, ww)
    Wp = jnp.maximum(jnp.sum(pos_w), 1e-30)

    # Cut points = ends of tie groups (scan includes the whole group).
    cum_tp = jnp.cumsum(pos_w)
    cum_fp = jnp.cumsum(neg_w)
    is_group_end = jnp.concatenate(
        [(s[1:] != s[:-1]), jnp.ones((1,), bool)]
    )
    recall = cum_tp / Wp
    precision = cum_tp / jnp.maximum(cum_tp + cum_fp, 1e-30)
    # Trapezoid over group-end points only; masked pairs contribute 0.
    # Previous group-end index for each position, by running max over ends:
    idx = jnp.arange(n)
    prev_end = jax.lax.associative_scan(jnp.maximum, jnp.where(is_group_end, idx, -1))
    prev_prev = jnp.concatenate([jnp.full((1,), -1, prev_end.dtype), prev_end[:-1]])
    r_prev = jnp.where(prev_prev >= 0, recall[jnp.maximum(prev_prev, 0)], 0.0)
    p_prev = jnp.where(prev_prev >= 0, precision[jnp.maximum(prev_prev, 0)], 1.0)
    contrib = jnp.where(
        is_group_end, (recall - r_prev) * 0.5 * (precision + p_prev), 0.0
    )
    return jnp.sum(contrib)


def peak_f1(scores: Array, labels: Array,
            weight: Optional[Array] = None) -> Array:
    """max over score thresholds t of F1(score >= t) — the reference's
    ``BinaryClassificationMetrics.fMeasureByThreshold().map(_._2).max``
    with every distinct score a candidate threshold; tied scores share one
    threshold (same group-end convention as auc_pr)."""
    w = _default_weight(scores, weight)
    order = jnp.argsort(-scores)  # descending: threshold sweep
    s, y, ww = scores[order], labels[order], w[order]
    pos_w = jnp.where(y > 0, ww, 0.0)
    tp = jnp.cumsum(pos_w)
    pp = jnp.cumsum(ww)  # predicted-positive mass at this threshold
    pos = jnp.sum(pos_w)
    is_group_end = jnp.concatenate(
        [(s[1:] != s[:-1]), jnp.ones((1,), bool)]
    )
    f1 = 2.0 * tp / jnp.maximum(pp + pos, 1e-30)
    return jnp.max(jnp.where(is_group_end, f1, -jnp.inf))


def rmse(scores: Array, labels: Array, weight: Optional[Array] = None) -> Array:
    w = _default_weight(scores, weight)
    tot = jnp.maximum(jnp.sum(w), 1e-30)
    return jnp.sqrt(jnp.sum(w * (scores - labels) ** 2) / tot)


def _mean_pointwise(loss_fn, scores, labels, weight):
    w = _default_weight(scores, weight)
    tot = jnp.maximum(jnp.sum(w), 1e-30)
    return jnp.sum(w * loss_fn(scores, labels)) / tot


def squared_loss_metric(scores, labels, weight=None):
    return _mean_pointwise(SquaredLoss.value, scores, labels, weight)


def logistic_loss_metric(scores, labels, weight=None):
    return _mean_pointwise(LogisticLoss.value, scores, labels, weight)


def poisson_loss_metric(scores, labels, weight=None):
    return _mean_pointwise(PoissonLoss.value, scores, labels, weight)


def precision_at_k(scores: Array, labels: Array, k: int) -> Array:
    """Unweighted P@k: fraction of positives among top-k scores
    (PrecisionAtKLocalEvaluator role)."""
    k = min(k, scores.shape[0])
    _, top = jax.lax.top_k(scores, k)
    return jnp.mean((labels[top] > 0).astype(jnp.float32))


# ---------------------------------------------------------------------------
# Grouped ("multi") evaluators: metric per group id, averaged over groups.
# ---------------------------------------------------------------------------


def grouped_auc(
    scores: Array,
    labels: Array,
    group_ids: Array,
    num_groups: int,
    weight: Optional[Array] = None,
) -> Array:
    """Mean per-group weighted AUC (reference MultiEvaluator + AUC local:
    groupBy(id) → local AUC → average). Groups lacking both classes are
    excluded from the average, matching the reference's filtered groupBy.

    One global lexicographic sort (group, score) + segment ops — no shuffle.
    Samples with group id < 0 (cold-start marker) are excluded entirely.
    """
    w = _default_weight(scores, weight)
    w = jnp.where(group_ids >= 0, w, 0.0)
    group_ids = jnp.maximum(group_ids, 0)
    n = scores.shape[0]
    # Sort by (group asc, score asc): combine into a single sort key by
    # sorting score first then stable-sorting group.
    order1 = jnp.argsort(scores, stable=True)
    g1 = group_ids[order1]
    order = order1[jnp.argsort(g1, stable=True)]
    s, y, ww, g = scores[order], labels[order], w[order], group_ids[order]

    pos_w = jnp.where(y > 0, ww, 0.0)
    neg_w = jnp.where(y > 0, 0.0, ww)

    # Tie groups within (group, score).
    new_tie = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         ((s[1:] != s[:-1]) | (g[1:] != g[:-1])).astype(jnp.int32)]
    )
    tid = jnp.cumsum(new_tie) - 1
    tie_neg = jax.ops.segment_sum(neg_w, tid, num_segments=n)
    cum_tie_neg = jnp.cumsum(tie_neg) - tie_neg  # exclusive, but global!
    # Subtract each group's starting cumulative so counts are per-group.
    grp_start_neg = jax.ops.segment_sum(neg_w, g, num_segments=num_groups)
    cum_grp_neg = jnp.cumsum(grp_start_neg) - grp_start_neg  # exclusive per group id
    below = cum_tie_neg[tid] - cum_grp_neg[g]
    frac = below + 0.5 * tie_neg[tid]

    num = jax.ops.segment_sum(pos_w * frac, g, num_segments=num_groups)
    Wp = jax.ops.segment_sum(pos_w, g, num_segments=num_groups)
    Wn = grp_start_neg
    valid = (Wp > 0) & (Wn > 0)
    auc_g = jnp.where(valid, num / jnp.maximum(Wp * Wn, 1e-30), 0.0)
    return jnp.sum(auc_g) / jnp.maximum(jnp.sum(valid), 1)


def grouped_precision_at_k(
    scores: Array, labels: Array, group_ids: Array, num_groups: int, k: int
) -> Array:
    """Mean per-group P@k (reference PrecisionAtKMultiEvaluator). Groups with
    fewer than k samples use all their samples."""
    # Rank within group: sort by (group, -score), positional rank per group.
    order1 = jnp.argsort(-scores, stable=True)
    g1 = group_ids[order1]
    order = order1[jnp.argsort(g1, stable=True)]
    y, g = labels[order], group_ids[order]
    n = scores.shape[0]
    idx = jnp.arange(n)
    # Start index of each group's run.
    is_start = jnp.concatenate([jnp.ones((1,), bool), g[1:] != g[:-1]])
    start_idx = jax.lax.associative_scan(jnp.maximum, jnp.where(is_start, idx, -1))
    rank = idx - start_idx  # 0-based rank within group
    in_top = rank < k
    hits = jax.ops.segment_sum(jnp.where(in_top & (y > 0), 1.0, 0.0), g, num_segments=num_groups)
    cnt = jax.ops.segment_sum(jnp.where(in_top, 1.0, 0.0), g, num_segments=num_groups)
    present = jax.ops.segment_sum(jnp.ones_like(hits[g]), g, num_segments=num_groups) > 0
    p = jnp.where(cnt > 0, hits / jnp.maximum(cnt, 1.0), 0.0)
    return jnp.sum(jnp.where(present, p, 0.0)) / jnp.maximum(jnp.sum(present), 1)


def evaluate(
    etype: EvaluatorType,
    scores: Array,
    labels: Array,
    weight: Optional[Array] = None,
    k: int = 10,
) -> Array:
    """Single-evaluator dispatch (EvaluatorFactory role)."""
    if etype == EvaluatorType.AUC:
        return auc_roc(scores, labels, weight)
    if etype == EvaluatorType.AUPR:
        return auc_pr(scores, labels, weight)
    if etype == EvaluatorType.RMSE:
        return rmse(scores, labels, weight)
    if etype == EvaluatorType.SQUARED_LOSS:
        return squared_loss_metric(scores, labels, weight)
    if etype == EvaluatorType.LOGISTIC_LOSS:
        return logistic_loss_metric(scores, labels, weight)
    if etype == EvaluatorType.POISSON_LOSS:
        return poisson_loss_metric(scores, labels, weight)
    if etype == EvaluatorType.PRECISION_AT_K:
        return precision_at_k(scores, labels, k)
    raise ValueError(f"unknown evaluator {etype}")
