from photon_tpu.evaluation.evaluators import (  # noqa: F401
    EvaluatorType,
    auc_roc,
    auc_pr,
    rmse,
    logistic_loss_metric,
    poisson_loss_metric,
    squared_loss_metric,
    precision_at_k,
    evaluate,
    grouped_auc,
    grouped_precision_at_k,
    metric_is_better,
)
from photon_tpu.evaluation.suite import EvaluationSuite  # noqa: F401
