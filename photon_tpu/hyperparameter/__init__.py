from photon_tpu.hyperparameter.search import (  # noqa: F401
    GaussianProcessSearch,
    RandomSearch,
)
from photon_tpu.hyperparameter.kernels import RBF, Matern52  # noqa: F401
from photon_tpu.hyperparameter.gp import GaussianProcessEstimator, GaussianProcessModel  # noqa: F401
from photon_tpu.hyperparameter.criteria import confidence_bound, expected_improvement  # noqa: F401
from photon_tpu.hyperparameter.tuner import HyperparameterTuner, get_tuner  # noqa: F401
