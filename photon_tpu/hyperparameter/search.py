"""Hyperparameter search: Sobol quasi-random + GP Bayesian (EI) search.

Parity targets: reference ``RandomSearch`` with Sobol draws + discrete
snapping (photon-lib hyperparameter/search/RandomSearch.scala:34-165+) and
``GaussianProcessSearch`` (fit GP posterior on observations, pick argmax
Expected Improvement over candidate draws,
search/GaussianProcessSearch.scala:52-196), plus the ``EvaluationFunction``
adapter (hyperparameter/EvaluationFunction.scala:25-57) and vector rescaling
(VectorRescaling.scala).

Improvement over the reference (SURVEY.md §2.7 item 5): ``find_batch``
proposes q points per round so candidate trainings can run concurrently on
the mesh instead of strictly sequentially.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_tpu.hyperparameter.criteria import expected_improvement
from photon_tpu.hyperparameter.gp import GaussianProcessEstimator

EvaluationFunction = Callable[[np.ndarray], float]
"""vector in [0,1]^d (rescaled hyperparameters) → evaluation value (lower
is better). The GameEstimator adapter lives in photon_tpu.estimators."""


@dataclasses.dataclass
class SearchRange:
    """Per-dimension range + optional discrete grid (reference discrete-param
    snapping RandomSearch.scala:171+). Values searched in [0,1] and rescaled."""

    lower: np.ndarray
    upper: np.ndarray
    discrete: Optional[Sequence[Optional[np.ndarray]]] = None  # per-dim grids

    def rescale(self, unit: np.ndarray) -> np.ndarray:
        x = self.lower + unit * (self.upper - self.lower)
        if self.discrete is not None:
            x = x.copy()
            for j, grid in enumerate(self.discrete):
                if grid is not None:
                    g = np.asarray(grid, float)
                    x[..., j] = g[np.argmin(np.abs(g[None, :] - x[..., j, None]), axis=-1)]
        return x

    def to_unit(self, x: np.ndarray) -> np.ndarray:
        return (x - self.lower) / np.maximum(self.upper - self.lower, 1e-30)


class RandomSearch:
    """Sobol quasi-random search (reference RandomSearch.scala:34-165)."""

    def __init__(self, dim: int, evaluator: EvaluationFunction,
                 search_range: Optional[SearchRange] = None, seed: int = 1):
        self.dim = dim
        self.evaluator = evaluator
        self.range = search_range or SearchRange(np.zeros(dim), np.ones(dim))
        self._sobol = qmc.Sobol(d=dim, scramble=True, seed=seed)
        self.observations: List[Tuple[np.ndarray, float]] = []

    # --- candidate generation ---

    def draw_candidates(self, n: int) -> np.ndarray:
        return self.range.rescale(self._sobol.random(n))

    def next_point(self) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def next_batch(self, q: int) -> np.ndarray:
        """(q, dim) proposals for one concurrent-evaluation round."""
        return self.draw_candidates(q)

    # --- driver loops (findWithPriors / find roles) ---

    def observe(self, x: np.ndarray, value: float) -> None:
        self.observations.append((np.asarray(x, float), float(value)))

    def find(self, n: int) -> Tuple[np.ndarray, float]:
        """Evaluate n points; return the best (point, value)."""
        from photon_tpu.obs.metrics import registry
        from photon_tpu.obs.trace import span

        registry().gauge("tuning_candidate_count").set(1)
        for i in range(n):
            with span(f"tuning/round{i}"):
                with span("propose"):
                    x = self.next_point()
                with span("train"):
                    value = self.evaluator(x)
                with span("observe"):
                    self.observe(x, value)
            registry().counter("tuning_rounds_total").inc()
        best = min(self.observations, key=lambda o: o[1])
        return best

    def find_batch(self, n_rounds: int, q: int,
                   batch_evaluator: Callable[[np.ndarray], Sequence[float]]) -> Tuple[np.ndarray, float]:
        """q proposals per round evaluated together (mesh-parallel tuning).
        Proposals come from ``next_batch`` — Sobol here, top-q EI in the
        Bayesian subclass — so each round refines on the last round's
        observations."""
        from photon_tpu.obs.metrics import registry
        from photon_tpu.obs.trace import span

        registry().gauge("tuning_candidate_count").set(q)
        for i in range(n_rounds):
            with span(f"tuning/round{i}"):
                with span("propose"):
                    X = self.next_batch(q)
                with span("train"):
                    values = [float(v) for v in batch_evaluator(X)]
                with span("observe"):
                    for x, v in zip(X, values):
                        self.observe(x, v)
            registry().counter("tuning_rounds_total").inc()
        return min(self.observations, key=lambda o: o[1])


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP posterior + EI argmax over Sobol candidates
    (reference GaussianProcessSearch.scala:52-196 uses 250/round; 256 here —
    Sobol sequences balance only at powers of two, and scipy warns
    otherwise). Falls back to pure Sobol until enough observations exist."""

    def __init__(self, dim: int, evaluator: EvaluationFunction,
                 search_range: Optional[SearchRange] = None, seed: int = 1,
                 num_candidates: int = 256, min_observations: int = 3,
                 estimator: Optional[GaussianProcessEstimator] = None):
        super().__init__(dim, evaluator, search_range, seed)
        self.num_candidates = num_candidates
        self.min_observations = min_observations
        self.estimator = estimator or GaussianProcessEstimator(seed=seed)

    def next_point(self) -> np.ndarray:
        if len(self.observations) < self.min_observations:
            return super().next_point()
        cand_unit, ei = self._ei_over_candidates()
        return self.range.rescale(cand_unit[int(np.argmax(ei))])

    def next_batch(self, q: int) -> np.ndarray:
        """Top-q EI candidates in one round (batch Bayesian proposals —
        the q Sobol candidates with the best acquisition, all from the same
        posterior; cheaper than q sequential constant-liar refits and
        adequate for the mesh-parallel training win)."""
        if len(self.observations) < self.min_observations:
            return super().next_batch(q)
        cand_unit, ei = self._ei_over_candidates()
        top = np.argsort(-ei)[:q]
        return self.range.rescale(cand_unit[top])

    def _ei_over_candidates(self) -> Tuple[np.ndarray, np.ndarray]:
        X = np.stack([o[0] for o in self.observations])
        y = np.array([o[1] for o in self.observations])
        model = self.estimator.fit(self.range.to_unit(X), y)
        cand_unit = self._sobol.random(self.num_candidates)
        mean, std = model.predict(cand_unit)
        return cand_unit, expected_improvement(mean, std, float(np.min(y)))
