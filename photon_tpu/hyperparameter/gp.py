"""Gaussian-process regression for Bayesian hyperparameter search.

Parity targets: reference ``GaussianProcessModel.predict`` via Cholesky
(photon-lib hyperparameter/estimators/GaussianProcessModel.scala:34-120),
``GaussianProcessEstimator.fit`` with slice-sampled kernel hyperparameters
(estimators/GaussianProcessEstimator.scala:36-142) and ``SliceSampler``
(estimators/SliceSampler.scala:52-210), and the Cholesky helpers the
reference keeps in util/Linalg.scala:33-100.

Predictions are averaged over an ensemble of kernel-hyperparameter samples
drawn by slice sampling from the GP marginal likelihood — the same
integrated-acquisition scheme the reference implements.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import numpy as np
import scipy.linalg

from photon_tpu.hyperparameter.kernels import Matern52, StationaryKernel


def cholesky_solve(L: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Solve A x = b given lower Cholesky L of A (Linalg.choleskySolve role)."""
    return scipy.linalg.cho_solve((L, True), b)


def log_marginal_likelihood(kernel: StationaryKernel, X: np.ndarray, y: np.ndarray) -> float:
    n = X.shape[0]
    K = kernel.kernel_matrix(X) + 1e-10 * np.eye(n)
    try:
        L = np.linalg.cholesky(K)
    except np.linalg.LinAlgError:
        return -np.inf
    alpha = cholesky_solve(L, y)
    return float(
        -0.5 * y @ alpha - np.sum(np.log(np.diag(L))) - 0.5 * n * np.log(2 * np.pi)
    )


class SliceSampler:
    """Univariate stepping-out slice sampler over each coordinate in turn
    (reference SliceSampler.scala:52-210)."""

    def __init__(self, log_density: Callable[[np.ndarray], float], step: float = 1.0,
                 max_steps: int = 16, rng: Optional[np.random.Generator] = None):
        self.log_density = log_density
        self.step = step
        self.max_steps = max_steps
        self.rng = rng or np.random.default_rng(0)

    def sample_coordinate(self, x: np.ndarray, dim: int) -> np.ndarray:
        f0 = self.log_density(x)
        if not np.isfinite(f0):
            return x
        log_y = f0 + np.log(self.rng.uniform(1e-12, 1.0))
        # Step out
        u = self.rng.uniform()
        lo = x[dim] - self.step * u
        hi = lo + self.step
        steps = 0

        def density_at(v):
            xx = x.copy()
            xx[dim] = v
            return self.log_density(xx)

        while density_at(lo) > log_y and steps < self.max_steps:
            lo -= self.step
            steps += 1
        steps = 0
        while density_at(hi) > log_y and steps < self.max_steps:
            hi += self.step
            steps += 1
        # Shrink
        for _ in range(64):
            v = self.rng.uniform(lo, hi)
            if density_at(v) > log_y:
                out = x.copy()
                out[dim] = v
                return out
            if v < x[dim]:
                lo = v
            else:
                hi = v
        return x

    def sample(self, x: np.ndarray) -> np.ndarray:
        for d in range(x.shape[0]):
            x = self.sample_coordinate(x, d)
        return x


@dataclasses.dataclass
class GaussianProcessModel:
    """Posterior predictive over an ensemble of fitted kernels."""

    X: np.ndarray  # (n, d) observed points
    kernels: List[StationaryKernel]
    Ls: List[np.ndarray]  # per-kernel Cholesky of K
    alphas: List[np.ndarray]  # per-kernel K⁻¹ y

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(mean, std) at query points, averaged over the kernel ensemble
        (GaussianProcessModel.scala:34-120)."""
        mus, vars_ = [], []
        for kernel, L, alpha in zip(self.kernels, self.Ls, self.alphas):
            Ks = kernel(self.X, Xs)  # (n, m)
            mu = Ks.T @ alpha
            v = scipy.linalg.solve_triangular(L, Ks, lower=True)
            var = np.maximum(
                kernel.amplitude + kernel.noise - np.sum(v * v, axis=0), 1e-12
            )
            mus.append(mu)
            vars_.append(var)
        mus = np.stack(mus)
        vars_ = np.stack(vars_)
        # Moment-matched mixture: E[y], Var[y] over ensemble members.
        mean = mus.mean(axis=0)
        var = vars_.mean(axis=0) + (mus**2).mean(axis=0) - mean**2
        return mean, np.sqrt(np.maximum(var, 1e-12))


class GaussianProcessEstimator:
    """Fit a GP with slice-sampled kernel hyperparameters
    (GaussianProcessEstimator.scala:36-142).

    Hyperparameters (log amplitude, log noise, log lengthscale) are sampled
    from the marginal likelihood; ``num_samples`` posterior kernels form the
    predictive ensemble.
    """

    def __init__(
        self,
        kernel_factory: Callable[..., StationaryKernel] = Matern52,
        num_samples: int = 3,
        burn_in: int = 5,
        seed: int = 0,
        normalize_y: bool = True,
    ):
        self.kernel_factory = kernel_factory
        self.num_samples = num_samples
        self.burn_in = burn_in
        self.seed = seed
        self.normalize_y = normalize_y

    def fit(self, X: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        X = np.asarray(X, float)
        y = np.asarray(y, float).ravel()
        y_mean, y_std = 0.0, 1.0
        if self.normalize_y and y.size > 1:
            y_mean = float(np.mean(y))
            y_std = float(np.std(y)) or 1.0
        yn = (y - y_mean) / y_std

        d = X.shape[1]

        def kernel_from_theta(theta: np.ndarray) -> StationaryKernel:
            return self.kernel_factory(
                amplitude=float(np.exp(theta[0])),
                noise=float(np.exp(theta[1])),
                lengthscale=np.exp(theta[2 : 2 + d]),
            )

        def log_density(theta: np.ndarray) -> float:
            # Weak log-normal priors keep the sampler in sane regions.
            prior = -0.5 * np.sum((theta / 3.0) ** 2)
            return log_marginal_likelihood(kernel_from_theta(theta), X, yn) + prior

        theta = np.zeros(2 + d)
        theta[1] = np.log(1e-3)
        sampler = SliceSampler(log_density, rng=np.random.default_rng(self.seed))
        for _ in range(self.burn_in):
            theta = sampler.sample(theta)

        kernels, Ls, alphas = [], [], []
        for _ in range(self.num_samples):
            theta = sampler.sample(theta)
            kern = kernel_from_theta(theta)
            K = kern.kernel_matrix(X) + 1e-10 * np.eye(X.shape[0])
            try:
                L = np.linalg.cholesky(K)
            except np.linalg.LinAlgError:
                continue
            kernels.append(kern)
            Ls.append(L)
            alphas.append(cholesky_solve(L, yn))
        if not kernels:  # degenerate fallback: default kernel
            kern = self.kernel_factory()
            K = kern.kernel_matrix(X) + 1e-6 * np.eye(X.shape[0])
            L = np.linalg.cholesky(K)
            kernels, Ls, alphas = [kern], [L], [cholesky_solve(L, yn)]

        model = GaussianProcessModel(X, kernels, Ls, alphas)
        model._y_mean, model._y_std = y_mean, y_std  # type: ignore[attr-defined]
        # Wrap predict to undo normalization.
        raw_predict = model.predict

        def predict(Xs):
            mu, sd = raw_predict(np.asarray(Xs, float))
            return mu * y_std + y_mean, sd * y_std

        model.predict = predict  # type: ignore[method-assign]
        return model
