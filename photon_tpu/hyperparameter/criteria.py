"""Acquisition criteria for Bayesian search.

Parity target: reference criteria (photon-lib hyperparameter/criteria/
ExpectedImprovement.scala, ConfidenceBound.scala). Minimization convention:
lower observed evaluation values are better (the reference transforms
maximize-metrics upstream).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


def expected_improvement(mean: np.ndarray, std: np.ndarray, best: float) -> np.ndarray:
    """EI for minimization: E[max(best - Y, 0)]."""
    std = np.maximum(std, 1e-12)
    z = (best - mean) / std
    return (best - mean) * norm.cdf(z) + std * norm.pdf(z)


def confidence_bound(mean: np.ndarray, std: np.ndarray, kappa: float = 2.0) -> np.ndarray:
    """Lower-confidence-bound score (higher is better for selection):
    -(mean - kappa·std)."""
    return -(mean - kappa * std)
