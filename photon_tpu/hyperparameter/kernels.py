"""Stationary GP covariance kernels.

Parity target: reference kernels (photon-lib hyperparameter/estimators/
kernels/StationaryKernel.scala, RBF.scala, Matern52.scala:44-80) —
amplitude/noise/lengthscale-parameterized stationary kernels with
automatic-relevance-determination lengthscales.

Host-side numpy in float64: the GP fits are tiny (tens of observations) and
driver-side, exactly as in the reference; the expensive part of tuning — the
candidate model trainings — runs on the TPU mesh.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class StationaryKernel:
    amplitude: float = 1.0
    noise: float = 1e-4
    lengthscale: np.ndarray = dataclasses.field(
        default_factory=lambda: np.ones(1)
    )  # scalar or per-dim (ARD)

    def _scaled_sqdist(self, X1: np.ndarray, X2: np.ndarray) -> np.ndarray:
        ls = np.broadcast_to(np.asarray(self.lengthscale, float), (X1.shape[1],))
        A = X1 / ls
        B = X2 / ls
        d2 = (
            np.sum(A * A, axis=1)[:, None]
            + np.sum(B * B, axis=1)[None, :]
            - 2.0 * A @ B.T
        )
        return np.maximum(d2, 0.0)

    def with_params(self, amplitude: float, noise: float, lengthscale: np.ndarray):
        return dataclasses.replace(
            self, amplitude=amplitude, noise=noise, lengthscale=lengthscale
        )

    def __call__(self, X1: np.ndarray, X2: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def kernel_matrix(self, X: np.ndarray) -> np.ndarray:
        """K(X, X) + noise·I."""
        return self(X, X) + self.noise * np.eye(X.shape[0])


@dataclasses.dataclass
class RBF(StationaryKernel):
    """Squared-exponential kernel (reference RBF.scala)."""

    def __call__(self, X1, X2):
        return self.amplitude * np.exp(-0.5 * self._scaled_sqdist(X1, X2))


@dataclasses.dataclass
class Matern52(StationaryKernel):
    """Matérn 5/2 kernel (reference Matern52.scala:44-80)."""

    def __call__(self, X1, X2):
        d = np.sqrt(self._scaled_sqdist(X1, X2))
        s5d = np.sqrt(5.0) * d
        return self.amplitude * (1.0 + s5d + 5.0 / 3.0 * d * d) * np.exp(-s5d)
