"""Hyperparameter configuration / prior-observation JSON (de)serialization.

Parity target: reference ``HyperparameterSerialization``
(photon-lib hyperparameter/HyperparameterSerialization.scala) and the
transform/scaling rules of ``VectorRescaling``
(hyperparameter/VectorRescaling.scala): a JSON config names the tuning mode
and the variables with {type, min, max, transform}; prior observations are
records of {param: value, ..., "evaluationValue": v}; transforms are LOG
(log10) and SQRT, applied per-index forward (raw → search space) and
backward (search space → raw).

Addition over the reference: ``observations_to_json`` — the tuner's search
history is persisted alongside TUNED models so later runs can seed
``prior_from_json`` with it (the reference can only read priors, not write
them).
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.hyperparameter.tuner import TuningMode

LOG_TRANSFORM = "LOG"
SQRT_TRANSFORM = "SQRT"


@dataclasses.dataclass(frozen=True)
class HyperparameterConfig:
    """Parsed tuning config (reference HyperparameterConfig)."""

    mode: Optional[TuningMode]  # None = NONE
    names: List[str]
    lower: np.ndarray  # raw-space bounds, per variable
    upper: np.ndarray
    discrete: Dict[int, int]  # index -> number of grid points (INT vars)
    transforms: Dict[int, str]  # index -> LOG | SQRT


def _apply(v: float, transform: str, forward: bool) -> float:
    if transform == LOG_TRANSFORM:
        return math.log10(v) if forward else 10.0**v
    if transform == SQRT_TRANSFORM:
        return math.sqrt(v) if forward else v * v
    raise ValueError(f"unknown transformation: {transform}")


def transform_forward(x: np.ndarray, transforms: Dict[int, str]) -> np.ndarray:
    """Raw values → search space (VectorRescaling.transformForward)."""
    out = np.array(x, float)
    for i, t in transforms.items():
        out[i] = _apply(float(out[i]), t, forward=True)
    return out


def transform_backward(x: np.ndarray, transforms: Dict[int, str]) -> np.ndarray:
    """Search space → raw values (VectorRescaling.transformBackward)."""
    out = np.array(x, float)
    for i, t in transforms.items():
        out[i] = _apply(float(out[i]), t, forward=False)
    return out


def config_from_json(json_config: str) -> HyperparameterConfig:
    """Parse a tuning config (HyperparameterSerialization.configFromJson)."""
    raw = json.loads(json_config)
    if not isinstance(raw, dict):
        raise ValueError("JSON config is not an object")
    mode_str = raw.get("tuning_mode", "NONE")
    mode = TuningMode[mode_str] if mode_str in TuningMode.__members__ else None

    variables = raw.get("variables")
    if not isinstance(variables, dict):
        raise ValueError("the hyper-parameter configurations must be a map")
    names, lower, upper = [], [], []
    discrete: Dict[int, int] = {}
    transforms: Dict[int, str] = {}
    for idx, (name, spec) in enumerate(variables.items()):
        if not isinstance(spec, dict) or "min" not in spec or "max" not in spec:
            raise ValueError(f"variable {name!r} needs numeric min/max")
        names.append(name)
        lo, hi = float(spec["min"]), float(spec["max"])
        lower.append(lo)
        upper.append(hi)
        if spec.get("type") == "INT":
            discrete[idx] = int(hi - lo) + 1
        t = spec.get("transform")
        if t is not None:
            if t not in (LOG_TRANSFORM, SQRT_TRANSFORM):
                raise ValueError(f"the transformation is not valid: {t}")
            transforms[idx] = t
    return HyperparameterConfig(
        mode=mode,
        names=names,
        lower=np.asarray(lower),
        upper=np.asarray(upper),
        discrete=discrete,
        transforms=transforms,
    )


def prior_from_json(
    prior_json: str,
    prior_default: Dict[str, float],
    names: Sequence[str],
) -> List[Tuple[np.ndarray, float]]:
    """Parse prior observations (HyperparameterSerialization.priorFromJson):
    {"records": [{<param>: <value>, ..., "evaluationValue": v}, ...]} →
    [(raw-space vector ordered by ``names``, value)]. Missing parameters
    fall back to ``prior_default``."""
    raw = json.loads(prior_json)
    if not isinstance(raw, dict) or not isinstance(raw.get("records"), list):
        raise ValueError('prior JSON must be {"records": [...]}')
    out = []
    for rec in raw["records"]:
        value = float(rec["evaluationValue"])
        vec = np.asarray(
            [float(rec[n]) if n in rec else float(prior_default[n]) for n in names],
            float,
        )
        out.append((vec, value))
    return out


def observations_to_json(
    observations: Sequence[Tuple[np.ndarray, float]],
    names: Sequence[str],
) -> str:
    """Serialize a search history to the prior-observation format."""
    records = []
    for x, v in observations:
        rec = {n: float(xi) for n, xi in zip(names, np.asarray(x, float))}
        rec["evaluationValue"] = float(v)
        records.append(rec)
    return json.dumps({"records": records}, indent=2)
