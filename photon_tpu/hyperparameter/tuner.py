"""Hyperparameter tuner SPI.

Parity target: reference ``HyperparameterTuner`` SPI +
``HyperparameterTunerFactory`` (DUMMY/ATLAS via Class.forName, photon-api
hyperparameter/tuner/HyperparameterTunerFactory.scala:19-40) and
``AtlasTuner`` (RandomSearch/GaussianProcessSearch dispatch,
tuner/AtlasTuner.scala:27-75).
"""

from __future__ import annotations

import enum
from typing import List, Optional, Tuple

import numpy as np

from photon_tpu.hyperparameter.search import (
    EvaluationFunction,
    GaussianProcessSearch,
    RandomSearch,
    SearchRange,
)


class TunerName(enum.Enum):
    DUMMY = "DUMMY"
    ATLAS = "ATLAS"


class TuningMode(enum.Enum):
    BAYESIAN = "BAYESIAN"
    RANDOM = "RANDOM"


class HyperparameterTuner:
    """search(n, dim, mode, eval_fn, prior observations) → best point/value
    (reference HyperparameterTuner.scala:39)."""

    def search(
        self,
        n: int,
        dim: int,
        mode: TuningMode,
        evaluator: EvaluationFunction,
        search_range: Optional[SearchRange] = None,
        prior_observations: Optional[List[Tuple[np.ndarray, float]]] = None,
        seed: int = 1,
        batch_size: int = 1,
    ) -> Tuple[Optional[np.ndarray], Optional[float], List[Tuple[np.ndarray, float]]]:
        raise NotImplementedError


class DummyTuner(HyperparameterTuner):
    """No-op (reference DummyTuner)."""

    def search(self, n, dim, mode, evaluator, search_range=None,
               prior_observations=None, seed=1, batch_size=1):
        return None, None, list(prior_observations or [])


class AtlasTuner(HyperparameterTuner):
    def search(self, n, dim, mode, evaluator, search_range=None,
               prior_observations=None, seed=1, batch_size=1):
        """``batch_size > 1`` proposes that many candidates per round and
        evaluates them together through ``evaluator.evaluate_batch`` (the
        vmapped mesh-parallel path — improvement over the reference's
        one-candidate-per-round loop, GameEstimator.scala:364-382)."""
        cls = GaussianProcessSearch if mode == TuningMode.BAYESIAN else RandomSearch
        search = cls(dim, evaluator, search_range, seed=seed)
        for x, v in prior_observations or []:
            search.observe(x, v)
        if batch_size > 1 and hasattr(evaluator, "evaluate_batch"):
            rounds = -(-n // batch_size)  # ceil: at least n evaluations
            best_x, best_v = search.find_batch(
                rounds, batch_size, evaluator.evaluate_batch
            )
        else:
            best_x, best_v = search.find(n)
        return best_x, best_v, search.observations


def get_tuner(name: TunerName) -> HyperparameterTuner:
    return AtlasTuner() if name == TunerName.ATLAS else DummyTuner()
