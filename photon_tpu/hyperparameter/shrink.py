"""Shrink the hyperparameter search range around the predicted best point.

Parity target: reference ``ShrinkSearchRange.getBounds``
(photon-client hyperparameter/ShrinkSearchRange.scala:28-80): rescale prior
observations to [0,1], fit a Matern52 Gaussian process, predict over a Sobol
candidate pool, take the candidate with the best predicted value, and return
``[best - radius, best + radius]`` (clipped to the unit cube, snapped for
discrete dims) scaled back to the original hyperparameter space.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_tpu.hyperparameter.gp import GaussianProcessEstimator
from photon_tpu.hyperparameter.kernels import Matern52
from photon_tpu.hyperparameter.search import SearchRange


def shrink_search_range(
    prior_observations: Sequence[Tuple[np.ndarray, float]],
    search_range: SearchRange,
    radius: float,
    candidate_pool_size: int = 1024,
    seed: int = 1,
    estimator: Optional[GaussianProcessEstimator] = None,
) -> SearchRange:
    """Return a SearchRange shrunk to ±radius (in unit-cube coordinates)
    around the GP-predicted best candidate.

    prior_observations are (hyperparameter vector in original space, value)
    pairs with lower = better, e.g. loaded from a prior run's tuning output
    (HyperparameterSerialization.priorFromJson role).
    """
    if not prior_observations:
        return search_range
    dim = len(search_range.lower)
    X = np.stack([search_range.to_unit(np.asarray(x, float))
                  for x, _ in prior_observations])
    y = np.array([v for _, v in prior_observations], float)

    est = estimator or GaussianProcessEstimator(kernel_factory=Matern52, seed=seed)
    model = est.fit(X, y)

    sobol = qmc.Sobol(d=dim, scramble=True, seed=seed)
    candidates = sobol.random(candidate_pool_size)
    mean, _ = model.predict(candidates)
    best = candidates[int(np.argmin(mean))]

    lower_unit = np.clip(best - radius, 0.0, 1.0)
    upper_unit = np.clip(best + radius, 0.0, 1.0)
    new_lower = search_range.rescale(lower_unit[None, :])[0]
    new_upper = search_range.rescale(upper_unit[None, :])[0]
    # Discrete snapping can collapse an interval; keep it ordered and non-empty.
    lo = np.minimum(new_lower, new_upper)
    hi = np.maximum(new_lower, new_upper)
    degenerate = hi <= lo
    if np.any(degenerate):
        lo = np.where(degenerate, search_range.lower, lo)
        hi = np.where(degenerate, search_range.upper, hi)
    return SearchRange(lo, hi, search_range.discrete)
