"""Continuous online experiment plane: GP-EI rounds over live traffic.

The reference ships its Bayesian (GP) tuner as an OFFLINE training feature:
propose λ, fit, score a holdout, repeat. This subsystem closes the loop
online instead — the pieces already exist, the experiment manager only
composes them:

1. propose — ``hyperparameter.search.GaussianProcessSearch.next_batch(q)``
   proposes q regularization points per round (top-q EI from one posterior).
2. train — each point trains a WARM-STARTED candidate generation via
   ``train/incremental.py`` (``optimization_config`` pins the exact λ), with
   ``publish=False``: a candidate never touches ``LATEST``.
3. serve — candidates load into the multi-version ``ServingEngine`` and
   shadow live primary traffic as N CONCURRENT lanes (engine lanes are
   deterministic fractional splits; versions differ only by table values,
   so N candidates cost zero marginal compiles).
4. observe — the GP's observation is the candidate's ONLINE quality
   (streaming AUC / loss from the quality plane's per-model-version
   windows), not an offline holdout: the tuner optimizes what production
   actually measures.
5. gate — a candidate whose online quality burns against the primary is
   POISONED (``mark_poisoned`` + lane stop; the same poison list the
   rollout watcher honors); the round winner promotes through the
   unchanged generation-manifest gate (``gate_and_publish`` → LATEST).

Crash-safety: the generation manifests ARE the experiment store. Every
candidate's manifest carries an ``experiment`` tag
(``{id, round, params, paramsKey, observation?, status}``); a killed
manager re-proposes each round deterministically (seeded Sobol + GP — see
tests/test_experiment.py for the cross-process determinism contract),
matches proposals against the tags by ``paramsKey``, and re-trains only
what has no durable record. There is no side state file to lose.

Fault sites (utils/faults.py plans):
- ``experiment.trained`` — fired after a candidate's training is durably
  complete (kill rules SIGKILL the manager mid-round; the resume drill).
- ``experiment.regress`` — fired before a candidate trains; a hit swaps
  the proposed point for a pathologically over-regularized configuration
  (the injected-regression candidate the quality burn must catch).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import math
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.estimators.config import (
    GameOptimizationConfig,
    RegularizationConfig,
)
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    SearchRange,
)
from photon_tpu.io.model_io import (
    experiment_generations,
    gate_and_publish,
    load_generation_manifest,
    mark_poisoned,
    update_generation_manifest,
)
from photon_tpu.obs.metrics import registry
from photon_tpu.obs.trace import span
from photon_tpu.utils import faults

logger = logging.getLogger(__name__)

# Reference defaults (GameEstimatorEvaluationFunction.scala:242-243).
DEFAULT_REG_WEIGHT_RANGE = (1e-4, 1e4)
DEFAULT_REG_ALPHA_RANGE = (0.0, 1.0)


def _short(version: object) -> str:
    return os.path.basename(str(version or "").rstrip("/"))


class ExperimentSpace:
    """Hyperparameter space of one experiment: per coordinate (sorted by
    id), log10(regularization weight) — plus the elastic-net alpha when the
    base configuration mixes (same tunable-slot rule as the offline
    adapter, estimators/evaluation_function.py). Maps GP vectors ↔
    ``GameOptimizationConfig`` and defines the stable ``paramsKey`` the
    crash-resume matching keys on."""

    def __init__(
        self,
        base_config: GameOptimizationConfig,
        reg_weight_range: Tuple[float, float] = DEFAULT_REG_WEIGHT_RANGE,
        reg_alpha_range: Tuple[float, float] = DEFAULT_REG_ALPHA_RANGE,
    ):
        self.base_config = base_config
        self._slots: List[Tuple[str, str]] = []  # (coordinate id, kind)
        lowers: List[float] = []
        uppers: List[float] = []
        for cid in sorted(base_config.reg):
            reg = base_config.reg[cid]
            if reg.weight <= 0.0:
                continue  # unregularized in the base config: not tuned
            self._slots.append((cid, "weight"))
            lowers.append(math.log10(reg_weight_range[0]))
            uppers.append(math.log10(reg_weight_range[1]))
            if reg.alpha > 0.0:
                self._slots.append((cid, "alpha"))
                lowers.append(reg_alpha_range[0])
                uppers.append(reg_alpha_range[1])
        if not self._slots:
            raise ValueError(
                "experiment space is empty: no coordinate in the base "
                "configuration carries a positive regularization weight"
            )
        self.search_range = SearchRange(
            np.asarray(lowers, float), np.asarray(uppers, float)
        )

    @property
    def dim(self) -> int:
        return len(self._slots)

    @property
    def names(self) -> List[str]:
        return [f"{cid}.{kind}" for cid, kind in self._slots]

    def params_from_vector(self, x: np.ndarray) -> Dict[str, float]:
        return {
            name: float(v) for name, v in zip(self.names, np.asarray(x, float))
        }

    def vector_to_config(self, x: np.ndarray) -> GameOptimizationConfig:
        if len(x) != self.dim:
            raise ValueError(f"dimension mismatch: {len(x)} != {self.dim}")
        reg = dict(self.base_config.reg)
        for (cid, kind), v in zip(self._slots, np.asarray(x, float)):
            old = reg[cid]
            if kind == "weight":
                reg[cid] = RegularizationConfig(
                    weight=float(10.0 ** v), alpha=old.alpha
                )
            else:
                reg[cid] = RegularizationConfig(
                    weight=old.weight, alpha=float(v)
                )
        return GameOptimizationConfig(reg)

    def regressed_config(self) -> GameOptimizationConfig:
        """A pathologically over-regularized configuration (every tuned
        weight → 1e8): the tuned coordinates shrink to ~zero, which is the
        injected-regression candidate the quality burn must poison."""
        reg = dict(self.base_config.reg)
        for cid, kind in self._slots:
            if kind == "weight":
                reg[cid] = RegularizationConfig(
                    weight=1e8, alpha=reg[cid].alpha
                )
        return GameOptimizationConfig(reg)


def point_key(params: Dict[str, float]) -> str:
    """Stable identity of one proposed point: sha1 over the
    name-sorted, 6-decimal-rounded params JSON. Rounding keeps the key
    identical across platforms whose float repr differs in the last ulps;
    6 decimals in log10-λ space is far below any training-visible
    difference."""
    canon = json.dumps(
        {k: round(float(v), 6) for k, v in sorted(params.items())},
        sort_keys=True,
    )
    return hashlib.sha1(canon.encode()).hexdigest()[:12]


@dataclasses.dataclass
class Candidate:
    """One proposed point's lifecycle within a round."""

    round: int
    index: int  # position within the round's proposal batch
    point: np.ndarray  # search-space vector (log10 weights / alphas)
    params: Dict[str, float]
    key: str
    generation: str
    model_dir: Optional[str] = None
    observation: Optional[float] = None
    source: Optional[str] = None  # online | stamped | penalty
    status: str = "proposed"  # proposed|trained|observed|poisoned
    poison_reason: Optional[str] = None
    reused: bool = False  # resumed from a durable manifest record


@dataclasses.dataclass
class ExperimentConfig:
    experiment_id: str
    publish_root: str
    rounds: int = 3
    candidates_per_round: int = 4
    seed: int = 7
    shadow_fraction: float = 0.5
    # Online observation: candidates must accumulate this many label-joined
    # events before their quality reading counts (None = the quality
    # plane's own min_events).
    min_events: Optional[int] = None
    observe_timeout_s: float = 120.0
    observe_poll_s: float = 0.25
    # Objective read from the quality plane, lower-is-better for the GP:
    # "loss" = windowed mean loss (logloss / Poisson deviance / task loss),
    # "auc" = 1 − windowed AUC (classification tasks).
    objective: str = "loss"
    # Quality burn (per-candidate poison gate): a candidate is poisoned
    # after `burn_checks` consecutive polls where its pooled windowed
    # quality is worse than the PRIMARY's by more than the bound
    # (auc_drop_bound for AUC; relative loss excess for loss objectives).
    auc_drop_bound: Optional[float] = None  # None = quality config's bound
    loss_burn_ratio: float = 0.5  # cand_loss > prim_loss · (1 + ratio)
    burn_checks: int = 2
    # Observation stamped for a poisoned candidate: worst finite
    # observation so far + this margin (recorded durably, so a resumed
    # manager replays the identical value).
    poison_margin: float = 1.0
    promote_winner: bool = True
    metric_tolerance: float = 0.02
    norm_drift_bound: float = 10.0
    gp_num_candidates: int = 256
    gp_min_observations: int = 3


class ExperimentManager:
    """Drives one experiment: GP rounds → warm-started candidate
    generations → concurrent shadow lanes → online observations → poison /
    promote. ``trainer`` must provide ``train(config, generation,
    extra_manifest) -> model_dir`` and ``load(model_dir) -> GameModel``
    (see :class:`IncrementalCandidateTrainer`); ``engine`` is the live
    :class:`~photon_tpu.serve.ServingEngine` (may be None for the
    train-only resume path — a manager without an engine can rebuild round
    state and train missing candidates but never observes)."""

    def __init__(
        self,
        config: ExperimentConfig,
        space: ExperimentSpace,
        trainer,
        engine=None,
    ):
        self.cfg = config
        self.space = space
        self.trainer = trainer
        self.engine = engine
        self.search = GaussianProcessSearch(
            dim=space.dim,
            evaluator=None,  # observations arrive from the quality plane
            search_range=space.search_range,
            seed=config.seed,
            num_candidates=config.gp_num_candidates,
            min_observations=config.gp_min_observations,
        )
        self.candidates: List[Candidate] = []
        self.reused_trained = 0
        self.reused_observed = 0
        self.trained = 0
        self.poisoned: List[str] = []
        self.winner: Optional[Candidate] = None

    # -- naming / durable records -------------------------------------------

    def _generation_name(self, rnd: int, key: str) -> str:
        return f"exp-{self.cfg.experiment_id}-r{rnd}-{key}"

    def _scan(self) -> Dict[Tuple[int, str], dict]:
        recs = experiment_generations(
            self.cfg.publish_root, self.cfg.experiment_id
        )
        return {(int(r["round"]), str(r["paramsKey"])): r for r in recs
                if "paramsKey" in r}

    def _experiment_tag(self, cand: Candidate) -> dict:
        return dict(
            id=self.cfg.experiment_id,
            round=cand.round,
            index=cand.index,
            params=cand.params,
            paramsKey=cand.key,
            status=cand.status,
        )

    def _stamp(self, cand: Candidate, **extra) -> None:
        if cand.model_dir is None:
            return
        tag = self._experiment_tag(cand)
        if cand.observation is not None:
            tag["observation"] = float(cand.observation)
            tag["observationSource"] = cand.source
        if cand.poison_reason:
            tag["poisonReason"] = cand.poison_reason
        tag.update(extra)
        update_generation_manifest(cand.model_dir, {"experiment": tag})

    # -- the round loop ------------------------------------------------------

    def run(self, train_only: bool = False) -> dict:
        """Run (or RESUME) the experiment to completion. Every round is
        re-proposed deterministically and matched against durable manifest
        records, so a crashed manager continues exactly where the disk
        says it stopped — completed candidates are never re-trained,
        stamped observations are never re-measured.

        ``train_only=True`` trains missing candidates round by round but
        never observes; it stops at the first round whose observations are
        not already durable (an engine-less manager cannot measure)."""
        reg = registry()
        recs = self._scan()
        for rnd in range(self.cfg.rounds):
            reg.gauge(
                "experiment_round", experiment=self.cfg.experiment_id
            ).set(rnd)
            with span(f"experiment/round{rnd}"):
                cands = self._propose_round(rnd, recs)
                self._train_missing(cands)
                pending = [c for c in cands if c.observation is None]
                if pending:
                    if train_only:
                        logger.info(
                            "experiment %s: train-only mode stopping at "
                            "round %d (%d candidates lack observations)",
                            self.cfg.experiment_id, rnd, len(pending),
                        )
                        return self.summary()
                    self._observe_round(cands)
                for c in sorted(cands, key=lambda c: c.index):
                    self.search.observe(c.point, float(c.observation))
            reg.counter(
                "experiment_rounds_total", experiment=self.cfg.experiment_id
            ).inc()
        if not train_only and self.cfg.promote_winner:
            self._promote_winner()
        return self.summary()

    def _propose_round(
        self, rnd: int, recs: Dict[Tuple[int, str], dict]
    ) -> List[Candidate]:
        X = self.search.next_batch(self.cfg.candidates_per_round)
        cands: List[Candidate] = []
        for i, x in enumerate(np.asarray(X, float)):
            params = self.space.params_from_vector(x)
            key = point_key(params)
            cand = Candidate(
                round=rnd, index=i, point=x, params=params, key=key,
                generation=self._generation_name(rnd, key),
            )
            rec = recs.get((rnd, key))
            if rec is not None:
                model_dir = os.path.join(
                    self.cfg.publish_root, rec["generation"]
                )
                if os.path.isdir(model_dir):
                    cand.model_dir = model_dir
                    cand.status = "trained"
                    cand.reused = True
                    self.reused_trained += 1
                if rec.get("observation") is not None:
                    cand.observation = float(rec["observation"])
                    cand.source = "stamped"
                    cand.status = str(rec.get("status") or "observed")
                    if cand.status == "poisoned":
                        cand.poison_reason = rec.get("poisonReason")
                    self.reused_observed += 1
            cands.append(cand)
        self.candidates.extend(cands)
        return cands

    def _train_missing(self, cands: Sequence[Candidate]) -> None:
        reg = registry()
        for cand in cands:
            if cand.model_dir is not None:
                continue
            config = self.space.vector_to_config(cand.point)
            regress = faults.injector().fire(
                "experiment.regress", label=cand.generation
            )
            tag = self._experiment_tag(cand)
            if regress is not None:
                config = self.space.regressed_config()
                tag["regressed"] = True
                logger.warning(
                    "fault experiment.regress: candidate %s trains the "
                    "over-regularized configuration", cand.generation,
                )
            with span("experiment/train"):
                cand.model_dir = self.trainer.train(
                    config, cand.generation, {"experiment": tag}
                )
            cand.status = "trained"
            self.trained += 1
            reg.counter(
                "experiment_candidates_trained_total",
                experiment=self.cfg.experiment_id,
            ).inc()
            # The kill site sits AFTER the durable train record: a SIGKILL
            # here is the worst case the resume discipline must absorb —
            # trained, observed by nobody, manifest already on disk.
            faults.check("experiment.trained", label=cand.generation)

    # -- online observation --------------------------------------------------

    def _quality_pool(self, version: str):
        """Pooled (over tenant / re_type) windowed accumulator for one
        model version, or None when the plane has nothing for it."""
        short = _short(version)
        out = None
        for key, acc in self.engine.quality.window_totals().items():
            if _short(key[0]) != short:
                continue
            if out is None:
                from photon_tpu.obs.quality import QualityAccumulator

                out = QualityAccumulator(acc.score_bins, acc.calibration_bins)
            out.merge(acc)
        return out

    def _objective_value(self, acc) -> Optional[float]:
        if acc is None or acc.count <= 0:
            return None
        if self.cfg.objective == "auc":
            auc = acc.auc()
            return None if auc is None else 1.0 - float(auc)
        loss = acc.mean_loss()
        return None if loss is None else float(loss)

    def _min_events(self) -> int:
        if self.cfg.min_events is not None:
            return int(self.cfg.min_events)
        return int(self.engine.quality.config.min_events)

    def _burns(self, cand_acc, prim_acc) -> Optional[str]:
        """Quality-burn verdict for one candidate vs the live primary over
        the same windows; None = healthy (or not enough evidence)."""
        min_events = self._min_events()
        if (cand_acc is None or prim_acc is None
                or cand_acc.count < min_events
                or prim_acc.count < min_events):
            return None
        bound = self.cfg.auc_drop_bound
        if bound is None:
            bound = float(self.engine.quality.config.auc_drop_bound)
        # AUC only separates the classification family; for linear /
        # Poisson the 0.5-threshold pos/neg split makes it noise, so the
        # burn verdict drops straight to the loss-ratio test.
        if self.engine.quality.config.task == "logistic":
            c_auc, p_auc = cand_acc.auc(), prim_acc.auc()
            if c_auc is not None and p_auc is not None:
                if c_auc < p_auc - bound:
                    return (
                        f"quality burn: candidate AUC {c_auc:.4f} < primary "
                        f"{p_auc:.4f} - {bound:.4f}"
                    )
                # A healthy AUC does NOT clear the candidate: ranking
                # survives a calibration collapse (scores shrunk toward
                # zero keep their sign and most of their order), so the
                # loss-ratio test below still applies.
        c_loss, p_loss = cand_acc.mean_loss(), prim_acc.mean_loss()
        if (c_loss is not None and p_loss is not None
                and c_loss > p_loss * (1.0 + self.cfg.loss_burn_ratio)):
            return (
                f"quality burn: candidate loss {c_loss:.4f} > primary "
                f"{p_loss:.4f} × {1.0 + self.cfg.loss_burn_ratio:.2f}"
            )
        return None

    def _observe_round(self, cands: Sequence[Candidate]) -> None:
        """Load the round's unobserved candidates as concurrent shadow
        lanes, wait for their online quality windows to fill, poison
        burners, stamp every observation durably."""
        if self.engine is None:
            raise RuntimeError(
                "experiment manager has no engine: cannot observe "
                "candidates online (train_only resume is the only "
                "engine-less mode)"
            )
        reg = registry()
        pending: List[Candidate] = []
        for cand in cands:
            if cand.observation is not None or cand.model_dir is None:
                continue
            try:
                with span("experiment/load_candidate"):
                    self.engine.load_version(
                        self.trainer.load(cand.model_dir),
                        model_version=cand.generation,
                    )
                self.engine.start_shadow(
                    cand.generation, self.cfg.shadow_fraction
                )
                pending.append(cand)
            except Exception as exc:  # noqa: BLE001 — candidate, not caller
                logger.warning(
                    "experiment %s: candidate %s failed to load (%s); "
                    "poisoning", self.cfg.experiment_id, cand.generation, exc,
                )
                self._poison(cand, f"load failed: {exc}")
        reg.gauge(
            "experiment_candidates_resident",
            experiment=self.cfg.experiment_id,
        ).set(len(pending))
        burn_strikes: Dict[str, int] = {}
        deadline = time.monotonic() + float(self.cfg.observe_timeout_s)
        min_events = self._min_events()
        while pending and time.monotonic() < deadline:
            time.sleep(self.cfg.observe_poll_s)
            prim_acc = self._quality_pool(self.engine.model_version)
            for cand in list(pending):
                acc = self._quality_pool(cand.generation)
                reason = self._burns(acc, prim_acc)
                if reason is not None:
                    strikes = burn_strikes.get(cand.key, 0) + 1
                    burn_strikes[cand.key] = strikes
                    if strikes >= max(1, int(self.cfg.burn_checks)):
                        self._poison(cand, reason)
                        pending.remove(cand)
                    continue
                burn_strikes[cand.key] = 0
                if acc is not None and acc.count >= min_events:
                    value = self._objective_value(acc)
                    if value is None:
                        continue  # e.g. single-class AUC window: keep waiting
                    cand.observation = value
                    cand.source = "online"
                    cand.status = "observed"
                    self._stamp(cand, events=acc.count)
                    self.engine.stop_shadow(cand.generation)
                    pending.remove(cand)
        for cand in pending:
            # Timed out: take whatever the window holds; a candidate with
            # zero joined labels observes the poison penalty (it measured
            # nothing, and the GP must not revisit blind spots for free).
            acc = self._quality_pool(cand.generation)
            value = self._objective_value(acc)
            if value is not None:
                cand.observation = value
                cand.source = "online"
                cand.status = "observed"
                self._stamp(cand, events=acc.count, timedOut=True)
                self.engine.stop_shadow(cand.generation)
            else:
                self._poison(cand, "no online observations before timeout")
        reg.gauge(
            "experiment_candidates_resident",
            experiment=self.cfg.experiment_id,
        ).set(0)

    def _penalty_value(self) -> float:
        finite = [
            c.observation for c in self.candidates
            if c.observation is not None
        ]
        worst = max(finite) if finite else 1.0
        return float(worst + self.cfg.poison_margin)

    def _poison(self, cand: Candidate, reason: str) -> None:
        cand.status = "poisoned"
        cand.poison_reason = reason
        cand.observation = self._penalty_value()
        cand.source = "penalty"
        self.poisoned.append(cand.generation)
        mark_poisoned(self.cfg.publish_root, cand.generation, reason)
        self._stamp(cand)
        if self.engine is not None:
            try:
                self.engine.stop_shadow(cand.generation)
            except Exception:  # noqa: BLE001 — lane may never have started
                pass
        registry().counter(
            "experiment_candidates_poisoned_total",
            experiment=self.cfg.experiment_id,
        ).inc()
        logger.warning(
            "experiment %s: POISONED candidate %s (%s)",
            self.cfg.experiment_id, cand.generation, reason,
        )

    # -- promotion -----------------------------------------------------------

    def best_candidate(self) -> Optional[Candidate]:
        live = [
            c for c in self.candidates
            if c.observation is not None and c.status != "poisoned"
        ]
        return min(live, key=lambda c: c.observation) if live else None

    def _promote_winner(self) -> None:
        best = self.best_candidate()
        if best is None:
            logger.warning(
                "experiment %s: no promotable candidate (all poisoned or "
                "unobserved)", self.cfg.experiment_id,
            )
            return
        gate = gate_and_publish(
            self.cfg.publish_root, best.generation,
            metric_tolerance=self.cfg.metric_tolerance,
            norm_drift_bound=self.cfg.norm_drift_bound,
        )
        self._stamp(best, winner=bool(gate.ok), gateReason=gate.reason)
        if not gate.ok:
            logger.warning(
                "experiment %s: winner %s REFUSED by the manifest gate "
                "(%s); LATEST unchanged", self.cfg.experiment_id,
                best.generation, gate.reason,
            )
            return
        self.winner = best
        registry().counter(
            "experiment_promotions_total", experiment=self.cfg.experiment_id
        ).inc()
        if self.engine is not None:
            try:
                if best.generation not in self.engine.versions:
                    self.engine.load_version(
                        self.trainer.load(best.model_dir),
                        model_version=best.generation,
                    )
                self.engine.promote(best.generation)
            except Exception as exc:  # noqa: BLE001 — LATEST already moved
                logger.warning(
                    "experiment %s: engine promotion of %s failed (%s); "
                    "the published LATEST pointer stands and the serving "
                    "watcher will adopt it", self.cfg.experiment_id,
                    best.generation, exc,
                )
        logger.info(
            "experiment %s: winner %s promoted (observation %.5f, %s)",
            self.cfg.experiment_id, best.generation, best.observation,
            json.dumps(best.params, sort_keys=True),
        )

    def summary(self) -> dict:
        best = self.best_candidate()
        return dict(
            experiment_id=self.cfg.experiment_id,
            rounds=self.cfg.rounds,
            candidates=[
                dict(
                    round=c.round, index=c.index, generation=c.generation,
                    params=c.params, paramsKey=c.key,
                    observation=c.observation, source=c.source,
                    status=c.status, poisonReason=c.poison_reason,
                    reused=c.reused,
                )
                for c in self.candidates
            ],
            trained=self.trained,
            reused_trained=self.reused_trained,
            reused_observed=self.reused_observed,
            poisoned=list(self.poisoned),
            winner=None if self.winner is None else self.winner.generation,
            best=None if best is None else dict(
                generation=best.generation, params=best.params,
                observation=best.observation,
            ),
        )


class IncrementalCandidateTrainer:
    """The production trainer: each candidate is one warm-started
    ``incremental_update`` on the delta batch, trained at EXACTLY the
    proposed configuration (``optimization_config``), published never
    (``publish=False`` — only the experiment winner moves LATEST, through
    the normal gate)."""

    def __init__(
        self,
        publish_root: str,
        batch,
        index_maps: Dict,
        entity_indexes: Dict,
        task,
        coordinate_configs: Sequence,
        update_sequence: Sequence[str],
        valid_batch=None,
        evaluation_suite=None,
        num_iterations: int = 1,
        locked_coordinates: Sequence[str] = (),
    ):
        self.publish_root = publish_root
        self.batch = batch
        self.index_maps = index_maps
        self.entity_indexes = entity_indexes
        self.task = task
        self.coordinate_configs = list(coordinate_configs)
        self.update_sequence = list(update_sequence)
        self.valid_batch = valid_batch
        self.evaluation_suite = evaluation_suite
        self.num_iterations = int(num_iterations)
        self.locked_coordinates = list(locked_coordinates)

    def train(
        self,
        config: GameOptimizationConfig,
        generation: str,
        extra_manifest: dict,
    ) -> str:
        from photon_tpu.train.incremental import incremental_update

        result = incremental_update(
            self.publish_root,
            self.batch,
            self.index_maps,
            self.entity_indexes,
            self.task,
            self.coordinate_configs,
            self.update_sequence,
            valid_batch=self.valid_batch,
            evaluation_suite=self.evaluation_suite,
            generation=generation,
            num_iterations=self.num_iterations,
            locked_coordinates=self.locked_coordinates,
            publish=False,
            extra_manifest=extra_manifest,
            optimization_config=config,
        )
        return result.model_dir

    def load(self, model_dir: str):
        from photon_tpu.io.model_io import load_resolved_game_model

        return load_resolved_game_model(
            model_dir, self.index_maps, self.entity_indexes,
            to_device=True, publish_root=self.publish_root,
        )


def experiment_summary(publish_root: str) -> dict:
    """Offline rollup of every experiment recorded in a publish root's
    generation manifests (the ``photon-tpu-obs experiments`` surface):
    per experiment — rounds, candidates with params/observations/status,
    poison reasons, and the winner when one promoted. Reads only the
    manifests + poison list; works with no server running."""
    from photon_tpu.io.model_io import load_poison_list

    poison = load_poison_list(publish_root)
    experiments: Dict[str, dict] = {}
    for rec in experiment_generations(publish_root):
        exp_id = str(rec.get("id"))
        exp = experiments.setdefault(
            exp_id,
            dict(id=exp_id, rounds=0, candidates=[], winner=None,
                 poisoned=[]),
        )
        gen = str(rec["generation"])
        entry = dict(
            generation=gen,
            round=int(rec.get("round", 0)),
            params=rec.get("params"),
            observation=rec.get("observation"),
            observationSource=rec.get("observationSource"),
            status=rec.get("status"),
            gate=(rec.get("gate") or {}).get("status"),
        )
        if gen in poison:
            entry["poisonReason"] = poison[gen]
            exp["poisoned"].append(gen)
        exp["candidates"].append(entry)
        exp["rounds"] = max(exp["rounds"], entry["round"] + 1)
        if rec.get("winner"):
            exp["winner"] = gen
    for exp in experiments.values():
        observed = [
            c for c in exp["candidates"]
            if c["observation"] is not None and c["status"] != "poisoned"
        ]
        exp["best"] = (
            min(observed, key=lambda c: c["observation"]) if observed else None
        )
    return dict(
        publish_root=publish_root,
        experiments=sorted(experiments.values(), key=lambda e: e["id"]),
    )
