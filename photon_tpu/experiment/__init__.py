"""Continuous online experiment plane (see manager.py for the design)."""

from photon_tpu.experiment.manager import (
    Candidate,
    ExperimentConfig,
    ExperimentManager,
    ExperimentSpace,
    IncrementalCandidateTrainer,
    experiment_summary,
    point_key,
)

__all__ = [
    "Candidate",
    "ExperimentConfig",
    "ExperimentManager",
    "ExperimentSpace",
    "IncrementalCandidateTrainer",
    "experiment_summary",
    "point_key",
]
