#!/bin/bash
# Probe the axon tunnel; when healthy, capture the round-4 evidence pack.
cd /root/repo
for i in $(seq 1 60); do
  if timeout 120 python -c 'import jax; jax.devices()' >/dev/null 2>&1; then
    echo "$(date +%T) tunnel healthy - starting bench pack (probe $i)"
    python -u bench.py --pack BENCH_PACK_r04.jsonl --trace-dir /root/repo/artifacts/trace_r04 > /root/repo/bench_pack_r04.log 2>&1
    echo "$(date +%T) pack finished rc=$?"
    exit 0
  fi
  echo "$(date +%T) tunnel wedged (probe $i)"
  sleep 540
done
echo "gave up after 60 probes"
exit 1
